"""Plan-family differentials: the parallel-in-time NFA families (scan =
associative-scan SFA, dfa = bit-packed multi-stride hybrid) must be
byte-identical to the sequential device kernel AND the host interpreter
across the pattern matrix — and ineligible patterns must provably fall
back (the plan reports the family it actually engaged plus the
ineligibility reason for every rejected family).

The matrix reuses the chunked-halo corpus (tests/test_nfa_chunked.py
QUERIES: counts, logicals, sequences — all ineligible shapes that must
force-fall-back) plus eligible chains covering static, threshold, and
hybrid hops, multi-stream chains, having, and cross-flush tail replay
(many small flushes)."""
import warnings

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.pattern_plan import DevicePatternPlan

HEAD = "define stream S (sym string, price double, volume int);\n" \
       "@info(name='q') "

# forced-family matrix: "seq" is exercised by every other pattern suite
# (it is the default device kernel there) and by the ineligible-fallback
# tests below; "chunk" has its own differential corpus
# (test_nfa_chunked.py) and rides three representative shapes here —
# keeping both out of the full matrix saves ~17 kernel compiles of
# tier-1 budget without losing coverage
FAMILIES = ("scan", "dfa")
# chunk × {threshold2, static-chain} shapes are test_nfa_chunked.py's own
# corpus; one hybrid (static + threshold hops) run here suffices
CHUNK_SUBSET = ("hybrid",)

# eligible chains: family -> expected engagement under force
ELIGIBLE = {
    "threshold2": (
        "from every e1=S[price > 100] -> e2=S[price > e1.price] "
        "within 1 sec select e1.price as p1, e2.price as p2 "
        "insert into Out;",
        {"seq", "chunk", "scan"}),
    "threshold3": (
        "from every e1=S[price > 100] -> e2=S[price > e1.price] -> "
        "e3=S[price > e2.price] within 2 sec "
        "select e1.price as p1, e2.price as p2, e3.price as p3 "
        "insert into Out;",
        {"seq", "chunk", "scan"}),
    "static2": (
        "from every e1=S[price > 120] -> e2=S[price < 95] within 1 sec "
        "select e1.price as a, e2.price as b insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "static3": (
        "from every e1=S[price > 118] -> e2=S[price < 96] -> "
        "e3=S[price > 124] within 2 sec "
        "select e1.price as a, e2.price as b, e3.price as c "
        "insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "hybrid": (
        "from every e1=S[price > 110] -> e2=S[price < 100] -> "
        "e3=S[price > e1.price] within 2 sec "
        "select e1.price as a, e2.price as b, e3.price as c "
        "insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "cross_threshold": (
        "from every e1=S[price > 105] -> e2=S[volume > 500] -> "
        "e3=S[price < e1.price] within 2 sec "
        "select e1.price as a, e2.volume as b, e3.price as c "
        "insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "le_threshold": (
        "from every e1=S[price > 115] -> e2=S[price <= e1.price] "
        "within 1 sec select e1.price as a, e2.price as b "
        "insert into Out;",
        {"seq", "chunk", "scan"}),
    "having": (
        "from every e1=S[price > 110] -> e2=S[price < 100] within 1 sec "
        "select e1.price as a, e2.price as b "
        "having a - b > 15.0 insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "computed_sel": (
        "from every e1=S[price > 112] -> e2=S[price < 98] within 1 sec "
        "select e1.price * 2.0 as d, e2.volume as v insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "string_sel": (
        "from every e1=S[price > 112] -> e2=S[price < 98] within 1 sec "
        "select e1.sym as s1, e2.sym as s2, e2.price as p "
        "insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    # ---- the expanded algebra (ISSUE 13): counts, logical AND/OR,
    # strict sequences, and non-`every` single arms all lower onto the
    # rank/select + prev-scan state chase now
    "count_head": (
        "from every e1=S[price > 110]<1:3> -> e2=S[price < 95] "
        "within 1 sec select e1[0].price as a, e1[last].price as b, "
        "e2.price as c insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "count_mid": (
        "from every e1=S[price > 118] -> e2=S[price > 112]<2:4> -> "
        "e3=S[price < 96] within 2 sec select e1.price as a, "
        "e2[0].price as b, e2[last].price as c, e3.price as d "
        "insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "count_final": (
        "from every e1=S[price > 118] -> e2=S[price < 97]<2:3> "
        "within 1 sec select e1.price as a, e2[last].price as b "
        "insert into Out;",
        {"seq", "chunk", "scan"}),
    "logical_and": (
        "from every e1=S[price > 120] -> e2=S[price < 100] and "
        "e3=S[price > 125] within 1 sec "
        "select e1.price as a, e2.price as b, e3.price as c "
        "insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "logical_or": (
        "from every e1=S[price > 122] -> e2=S[price < 95] or "
        "e3=S[price > 126] within 1 sec "
        "select e1.price as a, e2.price as b, e3.price as c "
        "insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "sequence": (
        "from every e1=S[price > 115], e2=S[price > e1.price] "
        "within 1 sec select e1.price as a, e2.price as b "
        "insert into Out;",
        {"seq", "chunk", "scan"}),
    "sequence_conj": (
        "from every e1=S[price > 110], "
        "e2=S[price > e1.price and volume > e1.volume] within 1 sec "
        "select e1.price as a, e2.price as b insert into Out;",
        {"seq", "chunk", "scan"}),
    "nonevery": (
        "from e1=S[price > 125] -> e2=S[price > e1.price] "
        "within 1 sec select e1.price as a, e2.price as b "
        "insert into Out;",
        {"seq", "scan"}),
    "count_null_idx": (
        "from every e1=S[price > 115]<1:3> -> e2=S[price < 95] "
        "within 1 sec select e1[1].price as b, e2.price as c "
        "insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
}

# ineligible shapes: every parallel family must REJECT them — forced
# requests fall back, outputs stay identical to the interpreter
INELIGIBLE = {
    "every_mid": (
        "from every e1=S[price > 127] -> every e2=S[price < 91] "
        "within 200 milliseconds select e1.price as a, e2.price as b "
        "insert into Out;",
        "every"),
    "optional_count": (
        "from every e1=S[price > 110] -> e2=S[price < 100]<0:3> -> "
        "e3=S[price > 124] within 1 sec select e1.price as a, "
        "e2[last].price as b, e3.price as c insert into Out;",
        "count quantifier"),
    "adjacent_counts": (
        "from every e1=S[price > 118]<1:2> -> e2=S[price < 97]<1:2> -> "
        "e3=S[price > 124] within 1 sec select e1[last].price as a, "
        "e2[last].price as b, e3.price as c insert into Out;",
        "adjacent"),
    "no_within": (
        "from every e1=S[price > 120] -> e2=S[price < 95] "
        "select e1.price as a, e2.price as b insert into Out;",
        "within"),
    "conjunction_step": (
        "from every e1=S[price > 110] -> "
        "e2=S[price > e1.price and volume > e1.volume] within 1 sec "
        "select e1.price as a, e2.price as b insert into Out;",
        "conjunct"),
}


def _run(head, q, n=900, batches=3, seed=11, dt=7, keys=4):
    mgr = SiddhiManager()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rt = mgr.create_app_runtime(head + HEAD + q)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(
        (e.timestamp,
         tuple(None if x is None else round(float(x), 3)
               if isinstance(x, float) else x for x in e.data))
        for e in evs))
    rt.start()
    plan = next((p for p in rt._plans
                 if isinstance(p, DevicePatternPlan)), None)
    fam = plan.family if plan is not None else None
    families = dict(plan.families) if plan is not None else {}
    rng = np.random.default_rng(seed)
    ih = rt.input_handler("S")
    ts0 = 1_700_000_000_000
    for b in range(batches):
        for j in range(n // batches):
            i = b * (n // batches) + j
            ih.send((f"K{rng.integers(0, keys)}",
                     float(np.round(rng.uniform(90, 130) * 4) / 4),
                     int(rng.integers(1, 1000))),
                    timestamp=ts0 + i * dt)
        rt.flush()
    mgr.shutdown()
    return fam, families, rows


@pytest.fixture(scope="module")
def host_rows():
    cache = {}

    def get(q):
        if q not in cache:
            _f, _e, rows = _run("@app:devicePatterns('never')\n", q)
            cache[q] = rows
        return cache[q]
    return get


# dfa provably rejects these (sequence/nonevery/final-count shapes) and
# falls back to scan — running them under a forced dfa would just re-run
# the scan differential, so they ride the slow lane only.  count_null_idx
# joins them: its dfa count machinery is count_head/count_mid's coverage
_DFA_FALLBACK = {"count_final", "sequence", "sequence_conj", "nonevery",
                 "count_null_idx"}


@pytest.mark.parametrize("name,fam", [
    pytest.param(n, f, marks=pytest.mark.slow)
    if f == "dfa" and n in _DFA_FALLBACK else (n, f)
    for n in ELIGIBLE for f in FAMILIES])
def test_eligible_differential(name, fam, host_rows):
    q, ok_fams = ELIGIBLE[name]
    used, families, dev = _run(
        f"@app:patternFamily('{fam}')\n@app:devicePatterns('always')\n", q)
    host = host_rows(q)
    if fam in ok_fams:
        assert used == fam, (name, fam, used, families)
    else:
        # provable fallback: the family rejected with a reason, and the
        # plan engaged a sound family instead
        assert families.get(fam) is not True, (name, fam)
        assert used != fam and used in ok_fams, (name, fam, used)
    assert len(dev) > 0, f"{name}: no matches — tape too easy?"
    assert dev == host, (name, fam, used, len(dev), len(host),
                         dev[:3], host[:3])


@pytest.mark.parametrize("name", CHUNK_SUBSET)
def test_chunk_family_differential(name, host_rows):
    q, ok_fams = ELIGIBLE[name]
    assert "chunk" in ok_fams
    used, _families, dev = _run(
        "@app:patternFamily('chunk')\n@app:devicePatterns('always')\n", q)
    assert used == "chunk"
    assert dev == host_rows(q), (name, len(dev))


@pytest.mark.parametrize("name", list(INELIGIBLE))
def test_ineligible_fallback(name, host_rows):
    # a forced scan and a forced dfa fall back to the SAME auto family
    # for these shapes, so one device run proves both rejections.
    # deviceChunkLanes(0) pins the fallback onto the sequential kernel —
    # chunk-vs-host for these exact shapes is test_nfa_chunked.py's job,
    # and the chunk compile would double this test's tier-1 cost
    q, reason_frag = INELIGIBLE[name]
    used, families, dev = _run(
        "@app:patternFamily('scan')\n@app:deviceChunkLanes(0)\n"
        "@app:devicePatterns('always')\n", q)
    host = host_rows(q)
    assert used == "seq", (name, used)
    for fam in ("scan", "dfa"):
        reason = families.get(fam)
        assert isinstance(reason, str) and reason, (name, fam, families)
        assert reason_frag.lower() in reason.lower(), (name, fam, reason)
    assert dev == host, (name, used, len(dev), len(host))


def test_unknown_family_name_is_a_build_error():
    from siddhi_tpu.core.planner import PlanError
    with pytest.raises(PlanError):
        SiddhiManager().create_app_runtime(
            "@app:patternFamily('warp')\n" + HEAD
            + ELIGIBLE["static2"][0])


def test_default_selection_prefers_parallel_families():
    q3, _ = ELIGIBLE["threshold2"]
    fam, families, _rows = _run(
        "@app:devicePatterns('always')\n", q3, n=300, batches=1)
    assert fam == "scan" and families["scan"] is True \
        and families["dfa"] is not True
    qs, _ = ELIGIBLE["static2"]
    fam, families, _rows = _run(
        "@app:devicePatterns('always')\n", qs, n=300, batches=1)
    assert fam == "scan" and families["dfa"] is True


@pytest.mark.slow
def test_cross_flush_tail_replay_many_small_flushes(host_rows):
    # many tiny flushes hammer the replay/dedup path: within 1 sec, dt=9
    # -> the tail spans several flushes of 60 events
    # fam -> a query the family genuinely engages for (dfa on threshold2
    # would just fall back to scan and re-test the same path)
    for fam, qname in (("scan", "threshold2"), ("dfa", "hybrid")):
        q, _ = ELIGIBLE[qname]
        _hf, _he, host = _run("@app:devicePatterns('never')\n",
                              q, n=900, batches=15, dt=9)
        used, _f, dev = _run(
            f"@app:patternFamily('{fam}')\n@app:devicePatterns('always')\n",
            q, n=900, batches=15, dt=9)
        assert used == fam
        assert dev == host, (fam, used, len(dev), len(host))


@pytest.mark.slow
def test_family_switch_regeometry_between_flushes():
    # stateless<->stateless family switches at flush boundaries are
    # output-invariant: start on the default (scan), switch to dfa
    # (eligible for the hybrid shape), then chunk, then back to scan,
    # and compare the stitched output with the host oracle
    q, _ = ELIGIBLE["hybrid"]
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:devicePatterns('always')\n" + HEAD + q)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(
        (e.timestamp, tuple(round(float(x), 3) for x in e.data))
        for e in evs))
    rt.start()
    plan = next(p for p in rt._plans if isinstance(p, DevicePatternPlan))
    assert plan.family == "scan"
    rng = np.random.default_rng(5)
    ih = rt.input_handler("S")
    ts0 = 1_700_000_000_000
    switches = {1: "dfa", 2: "chunk", 3: "scan"}
    for b in range(4):
        if b in switches:
            plan.regeometry(plan_family=switches[b])
            assert plan.family == switches[b]
        for j in range(400):
            i = b * 400 + j
            ih.send((f"K{rng.integers(0, 4)}",
                     float(np.round(rng.uniform(90, 130) * 4) / 4),
                     int(rng.integers(1, 1000))),
                    timestamp=ts0 + i * 7)
        rt.flush()
    mgr.shutdown()
    _f, _e, host = _run("@app:devicePatterns('never')\n", q,
                        n=1600, batches=4, seed=5)
    assert rows == host, (len(rows), len(host))


def test_family_gauges_in_statistics():
    q, _ = ELIGIBLE["static2"]
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:patternFamily('dfa')\n@app:devicePatterns('always')\n"
        + HEAD + q)
    rt.enable_stats(True)
    rt.start()
    ih = rt.input_handler("S")
    rng = np.random.default_rng(0)
    ts0 = 1_700_000_000_000
    for i in range(256):
        ih.send((f"K{i % 4}",
                 float(np.round(rng.uniform(90, 130) * 4) / 4), 10),
                timestamp=ts0 + i * 7)
    rt.flush()
    dev = rt.statistics().get("device", {}).get("q", {})
    mgr.shutdown()
    assert dev.get("plan_family") == "dfa"
    assert dev.get("dispatches_dfa", 0) >= 1
    assert "family_ineligible" not in dev or \
        isinstance(dev["family_ineligible"], dict)


@pytest.mark.slow
def test_out_of_order_expiry_matches_sequential():
    """The sequential kernel expires a waiting instance on ANY arriving
    event past the `within` horizon — even a non-matching one — so a
    later event with a REGRESSED timestamp must not complete it.  The
    pointer chase reproduces this via the killer-event query (review
    finding, confirmed divergent pre-fix: host/seq emitted [] while
    scan emitted the resurrected match)."""
    q = ("from every e1=S[price > 100] -> e2=S[price > e1.price] "
         "within 1 sec select e1.price as p1, e2.price as p2 "
         "insert into Out;")
    sends = [(0, 101.0), (2000, 50.0), (500, 150.0),   # killed instance
             (2100, 102.0), (2200, 103.0)]             # live pair

    def run(head):
        mgr = SiddhiManager()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            rt = mgr.create_app_runtime(head + HEAD + q)
        rows = []
        rt.add_callback("Out", lambda evs: rows.extend(
            tuple(e.data) for e in evs))
        rt.start()
        ih = rt.input_handler("S")
        ts0 = 1_700_000_000_000
        for dt, p in sends:
            ih.send(("K", p, 1), timestamp=ts0 + dt)
        rt.flush()
        mgr.shutdown()
        return rows

    host = run("@app:devicePatterns('never')\n")
    assert host == [(102.0, 103.0)]
    for fam in ("seq", "chunk", "scan", "dfa"):
        dev = run(f"@app:patternFamily('{fam}')\n"
                  "@app:devicePatterns('always')\n")
        assert dev == host, (fam, dev, host)


@pytest.mark.slow
def test_threshold_hop_nan_column_matches_sequential():
    """A NaN in the threshold column must behave like the sequential
    kernel's per-event compare (NaN compares False): it neither
    satisfies a hop nor poisons its segment-tree block (jnp.maximum
    would propagate NaN to every ancestor — review finding, confirmed
    divergent pre-fix)."""
    q = ("from every e1=S[price > 100] -> e2=S[price > e1.price] "
         "within 1 sec select e1.price as p1, e2.price as p2 "
         "insert into Out;")
    prices = [101.0, 90.0, 91.0, 92.0, float("nan"), 150.0,
              93.0, 94.0, 95.0, 160.0, 96.0, 97.0]

    def run(head):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(head + HEAD + q)
        rows = []
        rt.add_callback("Out", lambda evs: rows.extend(
            tuple(e.data) for e in evs))
        rt.start()
        ih = rt.input_handler("S")
        ts0 = 1_700_000_000_000
        for i, p in enumerate(prices):
            ih.send(("K", p, 1), timestamp=ts0 + i * 10)
        rt.flush()
        mgr.shutdown()
        return rows

    host = run("@app:devicePatterns('never')\n")
    assert host == [(101.0, 150.0), (150.0, 160.0)]
    for fam in ("scan", "dfa"):
        dev = run(f"@app:patternFamily('{fam}')\n"
                  "@app:devicePatterns('always')\n")
        assert dev == host, (fam, dev, host)


def test_classifier_agreement_build_vs_analysis():
    """Satellite: classify_shape (analysis time, AST only) and
    classify_parallel (build time, lowered kernel) must agree — same
    eligibility verdict AND same reason string — across the full
    eligible matrix and every ineligible shape, so SA08 can never
    disagree with the family the build actually selects."""
    from siddhi_tpu.core.nfa_parallel import classify_shape
    from siddhi_tpu.core.schema import StringTable
    from siddhi_tpu.query.parser import parse

    from siddhi_tpu.core.schema import StreamSchema
    for name, q in [(n, e[0]) for n, e in ELIGIBLE.items()] \
            + [(n, e[0]) for n, e in INELIGIBLE.items()]:
        app = parse(HEAD + q)
        query = app.execution_elements[0]
        schemas = {"S": StreamSchema.of(app.stream_definitions["S"])}
        shape = classify_shape(query.input, schemas, StringTable())
        mgr = SiddhiManager()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            rt = mgr.create_app_runtime(
                "@app:devicePatterns('always')\n" + HEAD + q)
        plan = next(p for p in rt._plans
                    if isinstance(p, DevicePatternPlan))
        for fam in ("chunk", "scan", "dfa"):
            assert plan.families[fam] == shape[fam], \
                (name, fam, plan.families[fam], shape[fam])
        mgr.shutdown()


PART_HEAD = "define stream S (sym string, price double, volume int);\n"
PART_Q = """partition with (sym of S)
begin
  @info(name='q')
  from every e1=S[price > 100] -> e2=S[price > e1.price]
    -> e3=S[price > e2.price] within 10 sec
  select e1.price as p1, e2.price as p2, e3.price as p3 insert into Out;
end;
"""


def _run_part(head, n=1200, batches=4, seed=3, dt=7, keys=37,
              plan_out=None):
    mgr = SiddhiManager()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rt = mgr.create_app_runtime(head + PART_HEAD + PART_Q)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(
        (e.timestamp, tuple(round(float(x), 3) for x in e.data))
        for e in evs))
    rt.start()
    plan = next((p for p in rt._plans
                 if isinstance(p, DevicePatternPlan)), None)
    rng = np.random.default_rng(seed)
    ih = rt.input_handler("S")
    ts0 = 1_700_000_000_000
    for b in range(batches):
        for j in range(n // batches):
            i = b * (n // batches) + j
            ih.send((f"K{rng.integers(0, keys)}",
                     float(np.round(rng.uniform(90, 130) * 4) / 4),
                     int(rng.integers(1, 1000))), timestamp=ts0 + i * dt)
        rt.flush()
    fam = plan.family if plan is not None else None
    if plan_out is not None:
        plan_out["metrics"] = plan.device_metrics() if plan else {}
        plan_out["explain"] = rt.explain()
    mgr.shutdown()
    # host clones deliver per instance: order differs from the device's
    # global completion order — compare as multisets with timestamps
    return fam, sorted(rows)


def test_partitioned_lanes_run_parallel_family_by_default():
    """The ISSUE 13 headline: a partitioned pattern (config 4's shape)
    runs a lane-vmapped parallel family BY DEFAULT, byte-identical to
    the per-key host clones, with zero D-FAMILY demotions."""
    _f, host = _run_part("@app:devicePatterns('never')\n")
    info: dict = {}
    fam, dev = _run_part("@app:partitionCapacity(64)\n", plan_out=info)
    assert fam == "scan", fam
    assert dev == host, (len(dev), len(host), dev[:3], host[:3])
    m = info["metrics"]
    assert m.get("dispatches_lane_vmapped", 0) >= 1
    assert m.get("lanes_last_dispatch", 0) >= 37
    ent = info["explain"]["queries"]["q"]
    assert ent["path"] == "device" and ent["family"] == "scan", ent
    assert not [d for d in ent.get("demotions", ())
                if d["rule_id"] in ("D-FAMILY", "D-PARTITION")], ent


@pytest.mark.slow
def test_partitioned_lanes_forced_dfa_differential():
    """The bit-packed family under the lane vmap: a static partitioned
    chain forced onto dfa matches the host clones byte-for-byte."""
    q_static = PART_Q.replace("e2=S[price > e1.price]",
                              "e2=S[price < 96]") \
                     .replace("e3=S[price > e2.price]",
                              "e3=S[price > 124]")

    def run(head):
        mgr = SiddhiManager()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            rt = mgr.create_app_runtime(head + PART_HEAD + q_static)
        rows = []
        rt.add_callback("Out", lambda evs: rows.extend(
            (e.timestamp, tuple(round(float(x), 3) for x in e.data))
            for e in evs))
        rt.start()
        plan = next((p for p in rt._plans
                     if isinstance(p, DevicePatternPlan)), None)
        rng = np.random.default_rng(3)
        ih = rt.input_handler("S")
        ts0 = 1_700_000_000_000
        for b in range(3):
            for j in range(300):
                i = b * 300 + j
                ih.send((f"K{rng.integers(0, 16)}",
                         float(np.round(rng.uniform(90, 130) * 4) / 4),
                         1), timestamp=ts0 + i * 7)
            rt.flush()
        fam = plan.family if plan is not None else None
        mgr.shutdown()
        return fam, sorted(rows)

    _f, host = run("@app:devicePatterns('never')\n")
    fam, dev = run("@app:patternFamily('dfa')\n"
                   "@app:partitionCapacity(32)\n")
    assert fam == "dfa", fam
    assert len(dev) > 0 and dev == host, (len(dev), len(host))


def test_partition_hot_add_reuses_lane_plan():
    """Satellite: a partitioned app that sees a NEW key mid-stream must
    reuse the vmapped lane plan — no per-key recompile (the (L, F) lane
    bucket absorbs it), no D-PARTITION demotion, and the placement
    plane keeps reporting one device query."""
    mgr = SiddhiManager()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rt = mgr.create_app_runtime(
            "@app:partitionCapacity(16)\n" + PART_HEAD + PART_Q)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(evs))
    rt.start()
    plan = next(p for p in rt._plans if isinstance(p, DevicePatternPlan))
    assert plan.family == "scan"
    rng = np.random.default_rng(9)
    ih = rt.input_handler("S")
    ts0 = 1_700_000_000_000
    for i in range(300):                       # 5 keys, warm compile
        ih.send((f"K{rng.integers(0, 5)}",
                 float(np.round(rng.uniform(90, 130) * 4) / 4), 1),
                timestamp=ts0 + i * 7)
    rt.flush()
    kern = plan._parallel_kernel()
    compiled_before = set(kern._block_cache)
    lanes_before = plan._lane_dispatches
    for i in range(300, 600):                  # 3 NEW keys hot-added
        ih.send((f"K{rng.integers(0, 8)}",
                 float(np.round(rng.uniform(90, 130) * 4) / 4), 1),
                timestamp=ts0 + i * 7)
    rt.flush()
    assert plan._lane_dispatches > lanes_before
    # 8 keys still fit the pow2 lane bucket of 8: the SAME compiled
    # (L, F) block served the new keys — zero recompiles
    assert set(kern._block_cache) == compiled_before, \
        (compiled_before, set(kern._block_cache))
    ent = rt.explain()["queries"]["q"]
    assert ent["path"] == "device" and ent["family"] == "scan"
    assert not [d for d in ent.get("demotions", ())
                if d["rule_id"] == "D-PARTITION"], ent
    assert len(plan._key_to_part) == 8
    mgr.shutdown()


def test_partitioned_quiet_lane_tail_held_aside():
    """Review regression: a lane with no new events this flush must NOT
    replay its tail (it cannot produce a new completion, and its old
    events would pin the shared i32 offset bases forever).  The held
    tail still resumes correctly when the key speaks again."""
    mgr = SiddhiManager()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rt = mgr.create_app_runtime(
            "@app:partitionCapacity(8)\n" + PART_HEAD + PART_Q.replace(
                "within 10 sec", "within 1 hour"))
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(
        tuple(e.data) for e in evs))
    rt.start()
    plan = next(p for p in rt._plans if isinstance(p, DevicePatternPlan))
    assert plan.family == "scan"
    ih = rt.input_handler("S")
    ts0 = 1_700_000_000_000
    ih.send(("A", 110.0, 1), timestamp=ts0)       # A arms a pending head
    ih.send(("B", 101.0, 1), timestamp=ts0 + 1)
    rt.flush()
    # flush 2: only B speaks — A's tail must be held aside, not gridded
    ih.send(("B", 102.0, 1), timestamp=ts0 + 2)
    rt.flush()
    tail_parts = set(plan._lane_tail["part"].tolist())
    assert len(tail_parts) == 2, tail_parts        # A held + B kept
    # flush 3: A resumes and completes its 3-chain from the held tail
    ih.send(("A", 120.0, 1), timestamp=ts0 + 3)
    ih.send(("A", 130.0, 1), timestamp=ts0 + 4)
    rt.flush()
    assert (110.0, 120.0, 130.0) in rows, rows
    mgr.shutdown()


def test_fused_lanes_run_parallel_family():
    """Fused multi-query groups (config 5's substrate) ride the SAME
    lane vmap: per-lane `__qparam` thresholds, events broadcast —
    byte-identical to per-query host matchers."""
    def app():
        parts = [PART_HEAD]
        for i in range(10):
            lo = 110 + (i % 5)
            parts.append(
                f"@info(name='q{i}') from every e1=S[price > {lo}] -> "
                f"e2=S[price > e1.price] within 1 sec "
                f"select e1.price as p1, e2.price as p2 "
                f"insert into Out{i % 3};")
        return "\n".join(parts) + "\n"

    def run(head):
        from siddhi_tpu.core.multi_query import MultiQueryDevicePatternPlan
        mgr = SiddhiManager()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            rt = mgr.create_app_runtime(head + app())
        rows = []
        for o in range(3):
            rt.add_callback(f"Out{o}", lambda evs, o=o: rows.extend(
                (o, e.timestamp, tuple(round(float(x), 3)
                                       for x in e.data)) for e in evs))
        rt.start()
        mq = next((p for p in rt._plans
                   if isinstance(p, MultiQueryDevicePatternPlan)), None)
        fam = mq.inner.family if mq is not None else None
        rng = np.random.default_rng(5)
        ih = rt.input_handler("S")
        ts0 = 1_700_000_000_000
        for b in range(3):
            for j in range(200):
                i = b * 200 + j
                ih.send((f"K{rng.integers(0, 4)}",
                         float(np.round(rng.uniform(90, 130) * 4) / 4),
                         int(rng.integers(1, 1000))),
                        timestamp=ts0 + i * 7)
            rt.flush()
        mgr.shutdown()
        return fam, sorted(rows)

    _f, host = run("@app:devicePatterns('never')\n")
    fam, dev = run("")
    assert fam == "scan", fam
    assert len(dev) > 0 and dev == host, (fam, len(dev), len(host))


def test_nonevery_single_arm_resolves_across_flushes():
    """A non-`every` chain arms ONCE, globally: a pending arm spans the
    flush boundary through the replay tail, and once resolved the host
    stops dispatching (the meta-row flag)."""
    q = ("from e1=S[price > 100] -> e2=S[price > e1.price] "
         "within 1 sec select e1.price as a, e2.price as b "
         "insert into Out;")
    sends = [(0, 90.0), (10, 101.0),            # flush 1: arm pending
             (20, 95.0), (30, 107.0),           # flush 2: completes
             (40, 120.0), (50, 130.0)]          # flush 3: must NOT match

    def run(head):
        mgr = SiddhiManager()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            rt = mgr.create_app_runtime(head + HEAD + q)
        rows = []
        rt.add_callback("Out", lambda evs: rows.extend(
            tuple(e.data) for e in evs))
        rt.start()
        ih = rt.input_handler("S")
        ts0 = 1_700_000_000_000
        plan = next((p for p in rt._plans
                     if isinstance(p, DevicePatternPlan)), None)
        for i, (dt, p) in enumerate(sends):
            ih.send(("K", p, 1), timestamp=ts0 + dt)
            if i % 2 == 1:
                rt.flush()
        rt.flush()
        done = (None if plan is None or plan._arm_done is None
                else bool(plan._arm_done.all()))
        mgr.shutdown()
        return rows, done

    host, _d = run("@app:devicePatterns('never')\n")
    assert host == [(101.0, 107.0)]
    dev, done = run("@app:patternFamily('scan')\n"
                    "@app:devicePatterns('always')\n")
    assert dev == host, (dev, host)
    assert done is True


def test_tuning_cache_plan_family_round_trip(tmp_path):
    from siddhi_tpu.core.autotune import (Geometry, TuningCache,
                                          validate_cache_data)
    c = TuningCache(str(tmp_path / "t.json"))
    c.put("pattern:abc", {"batch": 1024, "plan_family": "scan"},
          family="pattern")
    ent = c.peek("pattern:abc")
    assert ent["geometry"]["plan_family"] == "scan"
    g = Geometry.from_dict(ent["geometry"])
    assert g.plan_family == "scan" and g.batch == 1024
    import json
    data = json.load(open(str(tmp_path / "t.json")))
    assert validate_cache_data(data) == []
    data2 = json.loads(json.dumps(data))
    key = next(iter(data2["entries"]))
    data2["entries"][key]["geometry"]["plan_family"] = "bogus"
    assert validate_cache_data(data2)
