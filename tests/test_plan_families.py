"""Plan-family differentials: the parallel-in-time NFA families (scan =
associative-scan SFA, dfa = bit-packed multi-stride hybrid) must be
byte-identical to the sequential device kernel AND the host interpreter
across the pattern matrix — and ineligible patterns must provably fall
back (the plan reports the family it actually engaged plus the
ineligibility reason for every rejected family).

The matrix reuses the chunked-halo corpus (tests/test_nfa_chunked.py
QUERIES: counts, logicals, sequences — all ineligible shapes that must
force-fall-back) plus eligible chains covering static, threshold, and
hybrid hops, multi-stream chains, having, and cross-flush tail replay
(many small flushes)."""
import warnings

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.pattern_plan import DevicePatternPlan

HEAD = "define stream S (sym string, price double, volume int);\n" \
       "@info(name='q') "

# forced-family matrix: "seq" is exercised by every other pattern suite
# (it is the default device kernel there) and by the ineligible-fallback
# tests below; "chunk" has its own differential corpus
# (test_nfa_chunked.py) and rides three representative shapes here —
# keeping both out of the full matrix saves ~17 kernel compiles of
# tier-1 budget without losing coverage
FAMILIES = ("scan", "dfa")
# chunk × {threshold2, static-chain} shapes are test_nfa_chunked.py's own
# corpus; one hybrid (static + threshold hops) run here suffices
CHUNK_SUBSET = ("hybrid",)

# eligible chains: family -> expected engagement under force
ELIGIBLE = {
    "threshold2": (
        "from every e1=S[price > 100] -> e2=S[price > e1.price] "
        "within 1 sec select e1.price as p1, e2.price as p2 "
        "insert into Out;",
        {"seq", "chunk", "scan"}),
    "threshold3": (
        "from every e1=S[price > 100] -> e2=S[price > e1.price] -> "
        "e3=S[price > e2.price] within 2 sec "
        "select e1.price as p1, e2.price as p2, e3.price as p3 "
        "insert into Out;",
        {"seq", "chunk", "scan"}),
    "static2": (
        "from every e1=S[price > 120] -> e2=S[price < 95] within 1 sec "
        "select e1.price as a, e2.price as b insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "static3": (
        "from every e1=S[price > 118] -> e2=S[price < 96] -> "
        "e3=S[price > 124] within 2 sec "
        "select e1.price as a, e2.price as b, e3.price as c "
        "insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "hybrid": (
        "from every e1=S[price > 110] -> e2=S[price < 100] -> "
        "e3=S[price > e1.price] within 2 sec "
        "select e1.price as a, e2.price as b, e3.price as c "
        "insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "cross_threshold": (
        "from every e1=S[price > 105] -> e2=S[volume > 500] -> "
        "e3=S[price < e1.price] within 2 sec "
        "select e1.price as a, e2.volume as b, e3.price as c "
        "insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "le_threshold": (
        "from every e1=S[price > 115] -> e2=S[price <= e1.price] "
        "within 1 sec select e1.price as a, e2.price as b "
        "insert into Out;",
        {"seq", "chunk", "scan"}),
    "having": (
        "from every e1=S[price > 110] -> e2=S[price < 100] within 1 sec "
        "select e1.price as a, e2.price as b "
        "having a - b > 15.0 insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "computed_sel": (
        "from every e1=S[price > 112] -> e2=S[price < 98] within 1 sec "
        "select e1.price * 2.0 as d, e2.volume as v insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
    "string_sel": (
        "from every e1=S[price > 112] -> e2=S[price < 98] within 1 sec "
        "select e1.sym as s1, e2.sym as s2, e2.price as p "
        "insert into Out;",
        {"seq", "chunk", "scan", "dfa"}),
}

# ineligible shapes (from the chunked corpus + extras): every parallel
# family must REJECT them — forced requests fall back, outputs stay
# identical to the interpreter
INELIGIBLE = {
    "count": (
        "from every e1=S[price > 110]<1:3> -> e2=S[price < 95] "
        "within 1 sec select e1[0].price as a, e1[last].price as b, "
        "e2.price as c insert into Out;",
        "count quantifier"),
    "logical_and": (
        "from every e1=S[price > 120] -> e2=S[price < 100] and "
        "e3=S[price > 125] within 1 sec "
        "select e1.price as a, e2.price as b, e3.price as c "
        "insert into Out;",
        "logical"),
    "sequence": (
        "from every e1=S[price > 115], e2=S[price > e1.price] "
        "within 1 sec select e1.price as a, e2.price as b "
        "insert into Out;",
        "sequence"),
    "no_within": (
        "from every e1=S[price > 120] -> e2=S[price < 95] "
        "select e1.price as a, e2.price as b insert into Out;",
        "within"),
    "conjunction_step": (
        "from every e1=S[price > 110] -> "
        "e2=S[price > e1.price and volume > e1.volume] within 1 sec "
        "select e1.price as a, e2.price as b insert into Out;",
        "conjunct"),
}


def _run(head, q, n=900, batches=3, seed=11, dt=7, keys=4):
    mgr = SiddhiManager()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rt = mgr.create_app_runtime(head + HEAD + q)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(
        (e.timestamp,
         tuple(None if x is None else round(float(x), 3)
               if isinstance(x, float) else x for x in e.data))
        for e in evs))
    rt.start()
    plan = next((p for p in rt._plans
                 if isinstance(p, DevicePatternPlan)), None)
    fam = plan.family if plan is not None else None
    families = dict(plan.families) if plan is not None else {}
    rng = np.random.default_rng(seed)
    ih = rt.input_handler("S")
    ts0 = 1_700_000_000_000
    for b in range(batches):
        for j in range(n // batches):
            i = b * (n // batches) + j
            ih.send((f"K{rng.integers(0, keys)}",
                     float(np.round(rng.uniform(90, 130) * 4) / 4),
                     int(rng.integers(1, 1000))),
                    timestamp=ts0 + i * dt)
        rt.flush()
    mgr.shutdown()
    return fam, families, rows


@pytest.fixture(scope="module")
def host_rows():
    cache = {}

    def get(q):
        if q not in cache:
            _f, _e, rows = _run("@app:devicePatterns('never')\n", q)
            cache[q] = rows
        return cache[q]
    return get


@pytest.mark.parametrize("fam", FAMILIES)
@pytest.mark.parametrize("name", list(ELIGIBLE))
def test_eligible_differential(name, fam, host_rows):
    q, ok_fams = ELIGIBLE[name]
    used, families, dev = _run(
        f"@app:patternFamily('{fam}')\n@app:devicePatterns('always')\n", q)
    host = host_rows(q)
    if fam in ok_fams:
        assert used == fam, (name, fam, used, families)
    else:
        # provable fallback: the family rejected with a reason, and the
        # plan engaged a sound family instead
        assert families.get(fam) is not True, (name, fam)
        assert used != fam and used in ok_fams, (name, fam, used)
    assert len(dev) > 0, f"{name}: no matches — tape too easy?"
    assert dev == host, (name, fam, used, len(dev), len(host),
                         dev[:3], host[:3])


@pytest.mark.parametrize("name", CHUNK_SUBSET)
def test_chunk_family_differential(name, host_rows):
    q, ok_fams = ELIGIBLE[name]
    assert "chunk" in ok_fams
    used, _families, dev = _run(
        "@app:patternFamily('chunk')\n@app:devicePatterns('always')\n", q)
    assert used == "chunk"
    assert dev == host_rows(q), (name, len(dev))


@pytest.mark.parametrize("name", list(INELIGIBLE))
def test_ineligible_fallback(name, host_rows):
    # a forced scan and a forced dfa fall back to the SAME auto family
    # for these shapes, so one device run proves both rejections.
    # deviceChunkLanes(0) pins the fallback onto the sequential kernel —
    # chunk-vs-host for these exact shapes is test_nfa_chunked.py's job,
    # and the chunk compile would double this test's tier-1 cost
    q, reason_frag = INELIGIBLE[name]
    used, families, dev = _run(
        "@app:patternFamily('scan')\n@app:deviceChunkLanes(0)\n"
        "@app:devicePatterns('always')\n", q)
    host = host_rows(q)
    assert used == "seq", (name, used)
    for fam in ("scan", "dfa"):
        reason = families.get(fam)
        assert isinstance(reason, str) and reason, (name, fam, families)
        assert reason_frag.lower() in reason.lower(), (name, fam, reason)
    assert dev == host, (name, used, len(dev), len(host))


def test_unknown_family_name_is_a_build_error():
    from siddhi_tpu.core.planner import PlanError
    with pytest.raises(PlanError):
        SiddhiManager().create_app_runtime(
            "@app:patternFamily('warp')\n" + HEAD
            + ELIGIBLE["static2"][0])


def test_default_selection_prefers_parallel_families():
    q3, _ = ELIGIBLE["threshold2"]
    fam, families, _rows = _run(
        "@app:devicePatterns('always')\n", q3, n=300, batches=1)
    assert fam == "scan" and families["scan"] is True \
        and families["dfa"] is not True
    qs, _ = ELIGIBLE["static2"]
    fam, families, _rows = _run(
        "@app:devicePatterns('always')\n", qs, n=300, batches=1)
    assert fam == "scan" and families["dfa"] is True


@pytest.mark.slow
def test_cross_flush_tail_replay_many_small_flushes(host_rows):
    # many tiny flushes hammer the replay/dedup path: within 1 sec, dt=9
    # -> the tail spans several flushes of 60 events
    # fam -> a query the family genuinely engages for (dfa on threshold2
    # would just fall back to scan and re-test the same path)
    for fam, qname in (("scan", "threshold2"), ("dfa", "hybrid")):
        q, _ = ELIGIBLE[qname]
        _hf, _he, host = _run("@app:devicePatterns('never')\n",
                              q, n=900, batches=15, dt=9)
        used, _f, dev = _run(
            f"@app:patternFamily('{fam}')\n@app:devicePatterns('always')\n",
            q, n=900, batches=15, dt=9)
        assert used == fam
        assert dev == host, (fam, used, len(dev), len(host))


@pytest.mark.slow
def test_family_switch_regeometry_between_flushes():
    # stateless<->stateless family switches at flush boundaries are
    # output-invariant: start on the default (scan), switch to dfa
    # (eligible for the hybrid shape), then chunk, then back to scan,
    # and compare the stitched output with the host oracle
    q, _ = ELIGIBLE["hybrid"]
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:devicePatterns('always')\n" + HEAD + q)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(
        (e.timestamp, tuple(round(float(x), 3) for x in e.data))
        for e in evs))
    rt.start()
    plan = next(p for p in rt._plans if isinstance(p, DevicePatternPlan))
    assert plan.family == "scan"
    rng = np.random.default_rng(5)
    ih = rt.input_handler("S")
    ts0 = 1_700_000_000_000
    switches = {1: "dfa", 2: "chunk", 3: "scan"}
    for b in range(4):
        if b in switches:
            plan.regeometry(plan_family=switches[b])
            assert plan.family == switches[b]
        for j in range(400):
            i = b * 400 + j
            ih.send((f"K{rng.integers(0, 4)}",
                     float(np.round(rng.uniform(90, 130) * 4) / 4),
                     int(rng.integers(1, 1000))),
                    timestamp=ts0 + i * 7)
        rt.flush()
    mgr.shutdown()
    _f, _e, host = _run("@app:devicePatterns('never')\n", q,
                        n=1600, batches=4, seed=5)
    assert rows == host, (len(rows), len(host))


def test_family_gauges_in_statistics():
    q, _ = ELIGIBLE["static2"]
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:patternFamily('dfa')\n@app:devicePatterns('always')\n"
        + HEAD + q)
    rt.enable_stats(True)
    rt.start()
    ih = rt.input_handler("S")
    rng = np.random.default_rng(0)
    ts0 = 1_700_000_000_000
    for i in range(256):
        ih.send((f"K{i % 4}",
                 float(np.round(rng.uniform(90, 130) * 4) / 4), 10),
                timestamp=ts0 + i * 7)
    rt.flush()
    dev = rt.statistics().get("device", {}).get("q", {})
    mgr.shutdown()
    assert dev.get("plan_family") == "dfa"
    assert dev.get("dispatches_dfa", 0) >= 1
    assert "family_ineligible" not in dev or \
        isinstance(dev["family_ineligible"], dict)


@pytest.mark.slow
def test_out_of_order_expiry_matches_sequential():
    """The sequential kernel expires a waiting instance on ANY arriving
    event past the `within` horizon — even a non-matching one — so a
    later event with a REGRESSED timestamp must not complete it.  The
    pointer chase reproduces this via the killer-event query (review
    finding, confirmed divergent pre-fix: host/seq emitted [] while
    scan emitted the resurrected match)."""
    q = ("from every e1=S[price > 100] -> e2=S[price > e1.price] "
         "within 1 sec select e1.price as p1, e2.price as p2 "
         "insert into Out;")
    sends = [(0, 101.0), (2000, 50.0), (500, 150.0),   # killed instance
             (2100, 102.0), (2200, 103.0)]             # live pair

    def run(head):
        mgr = SiddhiManager()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            rt = mgr.create_app_runtime(head + HEAD + q)
        rows = []
        rt.add_callback("Out", lambda evs: rows.extend(
            tuple(e.data) for e in evs))
        rt.start()
        ih = rt.input_handler("S")
        ts0 = 1_700_000_000_000
        for dt, p in sends:
            ih.send(("K", p, 1), timestamp=ts0 + dt)
        rt.flush()
        mgr.shutdown()
        return rows

    host = run("@app:devicePatterns('never')\n")
    assert host == [(102.0, 103.0)]
    for fam in ("seq", "chunk", "scan", "dfa"):
        dev = run(f"@app:patternFamily('{fam}')\n"
                  "@app:devicePatterns('always')\n")
        assert dev == host, (fam, dev, host)


@pytest.mark.slow
def test_threshold_hop_nan_column_matches_sequential():
    """A NaN in the threshold column must behave like the sequential
    kernel's per-event compare (NaN compares False): it neither
    satisfies a hop nor poisons its segment-tree block (jnp.maximum
    would propagate NaN to every ancestor — review finding, confirmed
    divergent pre-fix)."""
    q = ("from every e1=S[price > 100] -> e2=S[price > e1.price] "
         "within 1 sec select e1.price as p1, e2.price as p2 "
         "insert into Out;")
    prices = [101.0, 90.0, 91.0, 92.0, float("nan"), 150.0,
              93.0, 94.0, 95.0, 160.0, 96.0, 97.0]

    def run(head):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(head + HEAD + q)
        rows = []
        rt.add_callback("Out", lambda evs: rows.extend(
            tuple(e.data) for e in evs))
        rt.start()
        ih = rt.input_handler("S")
        ts0 = 1_700_000_000_000
        for i, p in enumerate(prices):
            ih.send(("K", p, 1), timestamp=ts0 + i * 10)
        rt.flush()
        mgr.shutdown()
        return rows

    host = run("@app:devicePatterns('never')\n")
    assert host == [(101.0, 150.0), (150.0, 160.0)]
    for fam in ("scan", "dfa"):
        dev = run(f"@app:patternFamily('{fam}')\n"
                  "@app:devicePatterns('always')\n")
        assert dev == host, (fam, dev, host)


def test_tuning_cache_plan_family_round_trip(tmp_path):
    from siddhi_tpu.core.autotune import (Geometry, TuningCache,
                                          validate_cache_data)
    c = TuningCache(str(tmp_path / "t.json"))
    c.put("pattern:abc", {"batch": 1024, "plan_family": "scan"},
          family="pattern")
    ent = c.peek("pattern:abc")
    assert ent["geometry"]["plan_family"] == "scan"
    g = Geometry.from_dict(ent["geometry"])
    assert g.plan_family == "scan" and g.batch == 1024
    import json
    data = json.load(open(str(tmp_path / "t.json")))
    assert validate_cache_data(data) == []
    data2 = json.loads(json.dumps(data))
    key = next(iter(data2["entries"]))
    data2["entries"][key]["geometry"]["plan_family"] = "bogus"
    assert validate_cache_data(data2)
