"""Device NFA algebra completion (VERDICT r4 #3): absent-in-head, min-0
count heads, sequences containing absents, `every`-wrapped absents below
the head.  Each shape must (a) LOWER to the device kernel (no silent host
fallback) and (b) match the host oracle on scenario + fuzz tapes.

Reference semantics: StateInputStreamParser.java:77-143 composes every
state shape; AbsentStreamPreStateProcessor.java:60-115 arms waiting-time
deadlines from state registration (START registration for head absents).
"""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.pattern_plan import DevicePatternPlan

T0 = 1_000_000

HEAD = """
@app:playback
define stream S1 (sym string, price double);
define stream S2 (sym string, price double);
define stream S3 (sym string, price double);
"""


def _run(app, sends, marks=(), want_device=None):
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    if want_device is not None:
        got = any(isinstance(p, DevicePatternPlan) for p in rt._plans)
        assert got == want_device, \
            f"device-engaged={got}, wanted {want_device}"
    out = []
    rt.add_callback("O", lambda evs: out.extend(
        tuple(v for v in e.data) for e in evs))
    rt.start()
    rt.set_time(T0 - 1)
    events = sorted(sends, key=lambda s: s[2])
    marks = sorted(marks)
    mi = 0
    for sid, row, ts in events:
        while mi < len(marks) and marks[mi] <= ts:
            rt.set_time(marks[mi]); mi += 1
        rt.input_handler(sid).send(row, timestamp=ts)
        rt.flush()
    for t in marks[mi:]:
        rt.set_time(t)
    rt.flush()
    m.shutdown()
    return out


def both(body, sends, marks=(), device=True):
    """Run device-engaged (asserted) and host; outputs must match."""
    dev = _run("@app:devicePatterns('prefer')\n" + HEAD + body, sends,
               marks, want_device=device)
    host = _run("@app:devicePatterns('never')\n" + HEAD + body, sends,
                marks, want_device=False)
    assert dev == host, (len(dev), len(host), dev[:5], host[:5])
    return dev


# ---------------------------------------------------------------------------
# device engagement: the r4 fallback shapes now lower
# ---------------------------------------------------------------------------

ENGAGED_SHAPES = {
    "absent_head": "from not S1[price>20] for 1 sec -> e2=S2[price>30] "
                   "select e2.sym as b insert into O;",
    "every_absent_head": "from every not S1[price>10] for 1 sec -> "
                         "e2=S2[price>20] select e2.sym as b insert into O;",
    "seq_absent_tail": "from e1=S1[price>10], not S2[price>20] for 1 sec "
                       "select e1.sym as a insert into O;",
    "min0_head": "from e1=S1[price>10]<0:3> -> e2=S2[price>20] "
                 "select e2.sym as b insert into O;",
    "every_absent_mid": "from e1=S1[price>10] -> every not S2[price>20] "
                        "for 1 sec -> e3=S3[price>30] "
                        "select e1.sym as a, e3.sym as b insert into O;",
}


@pytest.mark.parametrize("name", list(ENGAGED_SHAPES))
def test_shape_lowers_to_device(name):
    m = SiddhiManager()
    rt = m.create_app_runtime("@app:devicePatterns('always')\n" + HEAD
                              + ENGAGED_SHAPES[name])
    assert any(isinstance(p, DevicePatternPlan) for p in rt._plans)
    m.shutdown()


# ---------------------------------------------------------------------------
# scenario matrix
# ---------------------------------------------------------------------------

def test_min0_head_zero_occurrences():
    """e2 alone matches; e1 emits null (zero collected occurrences)."""
    body = ("from e1=S1[price>10]<0:3> -> e2=S2[price>20] "
            "select e1.sym as a, e2.sym as b insert into O;")
    out = both(body, [("S2", ("B", 25.0), T0 + 100)])
    assert out == [(None, "B")]


def test_min0_head_with_occurrences():
    body = ("from e1=S1[price>10]<0:3> -> e2=S2[price>20] "
            "select e1.sym as a, e2.sym as b insert into O;")
    out = both(body, [("S1", ("A", 15.0), T0),
                      ("S1", ("A2", 16.0), T0 + 50),
                      ("S2", ("B", 25.0), T0 + 100)])
    assert out and out[0][1] == "B" and out[0][0] in ("A", "A2")


def test_seq_absent_mid_strictness():
    """Sequence `e1, not X for T, e2`: any event during the wait breaks
    contiguity (host strictness)."""
    body = ("from e1=S1[price>10], not S2[price>20] for 1 sec, "
            "e3=S3[price>30] select e1.sym as a, e3.sym as b insert into O;")
    # quiet wait, then deadline passes, then IMMEDIATE e3 -> match
    out = both(body, [("S1", ("A", 15.0), T0),
                      ("S3", ("C", 35.0), T0 + 1100)], [T0 + 1050])
    # an S3 arriving mid-wait breaks it
    out2 = both(body, [("S1", ("A", 15.0), T0),
                       ("S3", ("C", 35.0), T0 + 500),
                       ("S3", ("C2", 36.0), T0 + 1100)], [T0 + 1050])
    assert out == [("A", "C")] and out2 == []


def test_every_absent_head_rearms():
    """`every not A for 1s -> e2=B`: one arm per elapsed period."""
    body = ("from every not S1[price>10] for 1 sec -> e2=S2[price>20] "
            "select e2.sym as b insert into O;")
    # two quiet periods -> two armed clones; both Bs after -> each B
    # completes the clones pending at e2
    out = both(body, [("S2", ("B1", 25.0), T0 + 1200),
                      ("S2", ("B2", 26.0), T0 + 2400)], [T0 + 1100,
                                                         T0 + 2300])
    assert len(out) >= 2


def test_absent_head_snapshot_restore():
    """Init-slot state (armed deadline) survives snapshot/restore."""
    body = ("from not S1[price>20] for 1 sec -> e2=S2[price>30] "
            "select e2.sym as b insert into O;")
    app = "@app:devicePatterns('prefer')\n" + HEAD + body
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    assert any(isinstance(p, DevicePatternPlan) for p in rt._plans)
    out = []
    rt.add_callback("O", lambda evs: out.extend(tuple(e.data) for e in evs))
    rt.start()
    rt.set_time(T0 - 1)
    rt.input_handler("S2").send(("early", 35.0), timestamp=T0 + 100)
    rt.flush()                      # before the wait elapses: no match
    snap = rt.snapshot()
    m.shutdown()

    m2 = SiddhiManager()
    rt2 = m2.create_app_runtime(app)
    out2 = []
    rt2.add_callback("O", lambda evs: out2.extend(tuple(e.data)
                                                  for e in evs))
    rt2.start()
    rt2.restore(snap)
    rt2.set_time(T0 + 1100)         # wait elapses post-restore
    rt2.input_handler("S2").send(("late", 35.0), timestamp=T0 + 1200)
    rt2.flush()
    m2.shutdown()
    assert out == [] and out2 == [("late",)]


# ---------------------------------------------------------------------------
# differential fuzz: random tapes over the new shapes
# ---------------------------------------------------------------------------

FUZZ_SHAPES = [
    "from not S1[price>20] for 300 milliseconds -> e2=S2[price>30] "
    "select e2.sym as b insert into O;",
    "from every not S1[price>15] for 250 milliseconds -> e2=S2[price>25] "
    "select e2.sym as b insert into O;",
    "from e1=S1[price>10], not S2[price>20] for 200 milliseconds "
    "select e1.sym as a insert into O;",
    "from e1=S1[price>10]<0:2> -> e2=S2[price>20] "
    "select e2.sym as b insert into O;",
    "from e1=S1[price>10] -> every not S2[price>15] for 250 milliseconds "
    "-> e3=S3[price>20] select e1.sym as a, e3.sym as b insert into O;",
]


@pytest.mark.slow
@pytest.mark.parametrize("si", range(len(FUZZ_SHAPES)))
def test_fuzz_new_shapes(si):
    rng = np.random.default_rng(100 + si)
    body = FUZZ_SHAPES[si]
    streams = ["S1", "S2", "S3"]
    for trial in range(4):
        n = 40
        ts = T0 + np.cumsum(rng.integers(10, 120, size=n))
        sends = [(streams[int(rng.integers(0, 3))],
                  (f"E{i}", float(rng.integers(5, 40))), int(ts[i]))
                 for i in range(n)]
        marks = [int(ts[-1]) + 500]
        both(body, sends, marks)


# ---------------------------------------------------------------------------
# optional-count run after a counting state (r4 matrix entry, now lowered)
# ---------------------------------------------------------------------------

OPT_AFTER_COUNT = ("from e1=S1[price>10]<1:2> -> e2=S2[price>20]<0:2> -> "
                   "e3=S3[price>30] select e1[0].sym as a, e3.sym as c "
                   "insert into O;")


def test_opt_count_after_count_lowers():
    m = SiddhiManager()
    rt = m.create_app_runtime("@app:devicePatterns('always')\n" + HEAD
                              + OPT_AFTER_COUNT)
    assert any(isinstance(p, DevicePatternPlan) for p in rt._plans)
    m.shutdown()


def test_opt_count_after_count_zero_mid():
    """e1 then e3 directly (zero e2 occurrences) matches."""
    out = both(OPT_AFTER_COUNT, [("S1", ("A", 15.0), T0),
                                 ("S3", ("C", 35.0), T0 + 100)])
    assert out == [("A", "C")]


def test_opt_count_after_count_with_mids():
    out = both(OPT_AFTER_COUNT, [("S1", ("A", 15.0), T0),
                                 ("S2", ("B", 25.0), T0 + 50),
                                 ("S2", ("B2", 26.0), T0 + 60),
                                 ("S3", ("C", 35.0), T0 + 100)])
    assert out == [("A", "C")]


@pytest.mark.slow
def test_opt_count_after_count_fuzz():
    rng = np.random.default_rng(77)
    streams = ["S1", "S2", "S3"]
    for trial in range(6):
        n = 30
        ts = T0 + np.cumsum(rng.integers(5, 60, size=n))
        sends = [(streams[int(rng.integers(0, 3))],
                  (f"E{i}", float(rng.integers(5, 40))), int(ts[i]))
                 for i in range(n)]
        both(OPT_AFTER_COUNT, sends)
        both("from every e1=S1[price>10]<1:2> -> e2=S2[price>20]<0:2> -> "
             "e3=S3[price>30] select e1[0].sym as a, e3.sym as c "
             "insert into O;", sends)


# ---------------------------------------------------------------------------
# regressions from the r5 review
# ---------------------------------------------------------------------------

def test_rebase_preserves_no_first_sentinel():
    """A ts-base rebase (forced by a >LOCAL_SPAN jump) must not turn the
    NO_FIRST sentinel of an unstarted init slot into an ancient age."""
    body = ("from e1=S1[price>10]<0:3> -> e2=S2[price>20] "
            "within 1000 sec select e2.sym as b insert into O;")
    jump = 4_000_000_000            # > 2^30 ms: forces a rebase
    sends = [("S2", ("miss", 5.0), T0),             # arms, no match
             ("S2", ("B", 25.0), T0 + jump)]        # post-rebase match
    out = both(body, sends)
    assert out == [("B",)]


def test_absent_head_anchor_survives_restore():
    """The START anchor is part of the snapshot: restoring late must not
    re-anchor the wait at restore time (review r5)."""
    body = ("from not S1[price>20] for 1 sec -> e2=S2[price>30] "
            "select e2.sym as b insert into O;")
    app = "@app:devicePatterns('prefer')\n" + HEAD + body
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    rt.start()
    rt.set_time(T0)                 # anchor at T0 -> deadline T0+1000
    rt.flush()
    snap = rt.snapshot()
    m.shutdown()

    m2 = SiddhiManager()
    rt2 = m2.create_app_runtime(app)
    out2 = []
    rt2.add_callback("O", lambda evs: out2.extend(tuple(e.data)
                                                  for e in evs))
    rt2.start()
    rt2.set_time(T0 + 9000)         # restore-time is late
    rt2.restore(snap)
    rt2.set_time(T0 + 9500)         # original deadline long past
    rt2.input_handler("S2").send(("late", 35.0), timestamp=T0 + 9600)
    rt2.flush()
    m2.shutdown()
    host = _run("@app:devicePatterns('never')\n" + HEAD + body,
                [("S2", ("late", 35.0), T0 + 9600)], [T0 + 9500],
                want_device=False)
    assert out2 == [("late",)] == host


def test_absent_head_playback_anchor_without_set_time():
    """Pre-clock playback: the START anchor must come from the earliest
    buffered event, not the wall clock (review r5 — the wall anchor puts
    the deadline ~50 years past the tape)."""
    body = ("from not S1[price>20] for 1 sec -> e2=S2[price>30] "
            "select e2.sym as b insert into O;")

    def run(mode):
        m = SiddhiManager()
        rt = m.create_app_runtime(f"@app:devicePatterns('{mode}')\n"
                                  + HEAD + body)
        if mode == "prefer":
            assert any(isinstance(p, DevicePatternPlan) for p in rt._plans)
        out = []
        rt.add_callback("O", lambda evs: out.extend(tuple(e.data)
                                                    for e in evs))
        rt.start()                       # NO set_time: clock unanchored
        rt.input_handler("S1").send(("x", 5.0), timestamp=T0)  # not forbidden
        rt.flush()
        rt.set_time(T0 + 1100)           # wait elapses on the event timeline
        rt.input_handler("S2").send(("B", 35.0), timestamp=T0 + 1200)
        rt.flush()
        m.shutdown()
        return out
    dev, host = run("prefer"), run("never")
    assert dev == host == [("B",)]
