"""Admission control (siddhi_tpu/net/admission.py): token-bucket
determinism on a virtual clock, the three shed policies, ErrorStore
accounting (zero unaccounted loss) and the SLO rate-factor hook."""
import pytest

from siddhi_tpu.core.faults import ErrorStore
from siddhi_tpu.net.admission import (ADMIT, QUEUED, SHED, WAIT,
                                      AdmissionController, TokenBucket,
                                      Work, parse_bytes)


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def work(n=10, nbytes=100, rows=None, fed=None):
    return Work(n=n, nbytes=nbytes,
                feed=(lambda: fed.append(n)) if fed is not None
                else (lambda: None),
                rows=lambda: rows if rows is not None
                else [(0, ("x",) * 1)] * n,
                stream_id="S")


# ---------------------------------------------------------------------------
# token bucket
# ---------------------------------------------------------------------------

def test_bucket_refill_deterministic():
    clk = Clock()
    b = TokenBucket(rate=100, burst=50, clock=clk)
    assert b.try_take(50) == 0.0          # burst available
    wait = b.try_take(10)
    assert wait == pytest.approx(0.1)     # 10 tokens at 100/s
    clk.t += 0.1
    assert b.try_take(10) == 0.0
    clk.t += 0.05                         # 5 tokens
    assert b.try_take(10) == pytest.approx(0.05)


def test_bucket_unlimited():
    b = TokenBucket(rate=None, clock=Clock())
    assert b.try_take(10**9) == 0.0


def test_bucket_rate_factor_scales_refill():
    clk = Clock()
    b = TokenBucket(rate=100, burst=100, clock=clk)
    assert b.try_take(100) == 0.0
    b.set_factor(0.5)
    clk.t += 1.0                          # 50 tokens at half rate
    assert b.try_take(50) == 0.0
    assert b.try_take(1) > 0.0
    b.set_factor(5.0)                     # clamped to 1.0
    assert b.factor == 1.0
    b.set_factor(0.0001)                  # floored
    assert b.factor == pytest.approx(0.01)


def test_parse_bytes():
    assert parse_bytes("4 MB") == 4 << 20
    assert parse_bytes("512kb") == 512 << 10
    assert parse_bytes("65536") == 65536
    assert parse_bytes("1 G") == 1 << 30
    assert parse_bytes(None) == 0


# ---------------------------------------------------------------------------
# policies
# ---------------------------------------------------------------------------

def test_unlimited_admits_everything():
    c = AdmissionController("S", clock=Clock())
    for _ in range(100):
        assert c.offer(work()).action == ADMIT
    m = c.metrics()
    assert m["admitted_events"] == m["events_in"] == 1000
    assert m["shed_events"] == 0


def test_shed_policy_accounts_into_error_store():
    clk = Clock()
    store = ErrorStore()
    c = AdmissionController("S", rate_limit=100, burst=20, policy="shed",
                            error_store=store, clock=clk,
                            now_ms=lambda: 123)
    assert c.offer(work(n=20)).action == ADMIT
    d = c.offer(work(n=20, rows=[(1, ("a",)), (2, ("b",))]))
    assert d.action == SHED
    assert len(store) == 1
    ent = store.entries("S")[0]
    assert ent.point == "net.shed" and len(ent.events) == 2
    m = c.metrics()
    assert m["shed_events"] == 20 and m["shed_frames"] == 1
    # zero unaccounted loss: in == admitted + shed
    assert m["events_in"] == m["admitted_events"] + m["shed_events"]


def test_block_policy_returns_wait_then_admits():
    clk = Clock()
    c = AdmissionController("S", rate_limit=100, burst=10, policy="block",
                            clock=clk)
    assert c.offer(work(n=10)).action == ADMIT
    d = c.offer(work(n=10))
    assert d.action == WAIT and d.wait_s == pytest.approx(0.1)
    clk.t += 0.1

    def sleep(s):                         # virtual sleep advances clock
        clk.t += s
    assert c.submit(work(n=10), sleep=sleep).action == ADMIT


def test_block_submit_sheds_on_stop():
    clk = Clock()
    store = ErrorStore()
    c = AdmissionController("S", rate_limit=1, burst=1, policy="block",
                            error_store=store, clock=clk,
                            now_ms=lambda: 0)
    assert c.submit(work(n=1)).action == ADMIT
    d = c.submit(work(n=1), stop=lambda: True, sleep=lambda s: None)
    assert d.action == SHED and len(store) == 1


def test_oldest_policy_queues_then_drains_in_order():
    clk = Clock()
    fed: list = []
    c = AdmissionController("S", rate_limit=100, burst=10, policy="oldest",
                            clock=clk)
    assert c.offer(work(n=10, fed=fed)).action == ADMIT
    assert c.offer(work(n=10, fed=fed)).action == QUEUED
    assert c.offer(work(n=10, fed=fed)).action == QUEUED
    assert c.metrics()["pending_frames"] == 2
    clk.t += 0.1                          # one frame's tokens
    ready = c.pump()
    assert len(ready) == 1
    ready[0].feed()                       # consumers feed what they drain
    clk.t += 0.1
    nxt = c.pump()
    assert len(nxt) == 1
    nxt[0].feed()
    assert c.metrics()["pending_frames"] == 0
    assert fed == [10, 10]


def test_oldest_policy_inflight_blocks_new_admits():
    """Drained-but-not-yet-fed work still holds FIFO order: a frame
    arriving while another thread feeds the drain must queue behind it,
    not admit around it (same-producer frames would reorder)."""
    clk = Clock()
    fed: list = []
    c = AdmissionController("S", rate_limit=100, burst=10, policy="oldest",
                            clock=clk)
    assert c.offer(work(n=10, fed=fed)).action == ADMIT      # burst
    assert c.offer(work(n=10, fed=fed)).action == QUEUED     # W1 parks
    clk.t += 0.1
    drained = c.pump()                    # W1 handed out, NOT fed yet
    assert len(drained) == 1
    clk.t += 0.1                          # tokens exist for more
    d = c.offer(work(n=10, fed=fed))      # W2 must not jump W1
    assert d.action == QUEUED and d.ready == []
    assert c.pump() == []                 # still gated on W1's feed
    drained[0].feed()                     # W1 lands
    nxt = c.pump()                        # now W2 drains
    assert len(nxt) == 1
    nxt[0].feed()
    assert fed == [10, 10]
    assert c.metrics()["pending_frames"] == 0


def test_oldest_policy_lone_oversized_frame_sheds_not_queued():
    """A single frame larger than the pending watermark sheds outright —
    the decision must SAY shed (REST maps QUEUED to 202 'queued', a
    promise the feed would never keep)."""
    clk = Clock()
    store = ErrorStore()
    c = AdmissionController("S", rate_limit=100, burst=10, policy="oldest",
                            max_pending_bytes=100, error_store=store,
                            clock=clk, now_ms=lambda: 0)
    assert c.offer(work(n=10, nbytes=50)).action == ADMIT    # drain burst
    d = c.offer(work(n=10, nbytes=500))   # exceeds the watermark alone
    assert d.action == SHED
    assert len(store) == 1
    m = c.metrics()
    assert m["pending_frames"] == 0 and m["pending_bytes"] == 0


def test_oldest_policy_evicts_oldest_on_watermark():
    clk = Clock()
    store = ErrorStore()
    c = AdmissionController("S", rate_limit=100, burst=10, policy="oldest",
                            max_pending_bytes=250, error_store=store,
                            clock=clk, now_ms=lambda: 0)
    assert c.offer(work(n=10, nbytes=100,
                        rows=[(0, ("first",))])).action == ADMIT
    c.offer(work(n=10, nbytes=100, rows=[(1, ("second",))]))
    c.offer(work(n=10, nbytes=100, rows=[(2, ("third",))]))
    d = c.offer(work(n=10, nbytes=100, rows=[(3, ("fourth",))]))
    assert d.action == QUEUED
    # watermark 250: queuing the fourth (300 pending bytes) evicted the
    # OLDEST pending frame ("second" — "first" was admitted)
    assert len(store) == 1
    assert store.entries("S")[0].events[0][1] == ("second",)
    assert c.metrics()["pending_bytes"] == 200


def test_flush_pending_to_store():
    clk = Clock()
    store = ErrorStore()
    c = AdmissionController("S", rate_limit=1, burst=1, policy="oldest",
                            error_store=store, clock=clk, now_ms=lambda: 0)
    c.offer(work(n=1))
    c.offer(work(n=1))
    c.offer(work(n=1))
    assert c.flush_pending_to_store() == 2
    assert len(store) == 2
    assert c.metrics()["pending_frames"] == 0


def test_unknown_policy_rejected():
    with pytest.raises(ValueError, match="shed.policy"):
        AdmissionController("S", policy="yolo")


def test_frame_larger_than_burst_sheds_never_spins():
    """A frame with more events than the bucket can EVER hold must shed
    (accounted) immediately — 'block' would otherwise spin forever and
    'oldest' would jam its queue head."""
    for policy in ("block", "shed", "oldest"):
        clk = Clock()
        store = ErrorStore()
        c = AdmissionController("S", rate_limit=100, burst=50,
                                policy=policy, error_store=store,
                                clock=clk, now_ms=lambda: 0)
        d = c.submit(work(n=51), sleep=lambda s: (_ for _ in ()).throw(
            AssertionError("must not wait")))
        assert d.action == SHED, policy
        assert len(store) == 1
        m = c.metrics()
        assert m["events_in"] == m["admitted_events"] + m["shed_events"]
        # and a normal frame still admits afterwards
        assert c.submit(work(n=50)).action == ADMIT, policy


def test_slo_rate_factor_hook():
    clk = Clock()
    c = AdmissionController("S", rate_limit=1000, burst=100, clock=clk)
    c.set_rate_factor(0.25)
    assert c.metrics()["rate_factor"] == 0.25
    assert c.offer(work(n=100)).action == ADMIT    # burst unaffected
    clk.t += 0.2                                   # 1000*0.25*0.2 = 50
    assert c.offer(work(n=50)).action == ADMIT
    assert c.offer(work(n=1)).action == WAIT


def test_bucket_rate_zero_admits_nothing():
    """rate=0 is a declared quarantine — admit NOTHING, shed everything
    accounted — not unlimited: only rate=None means no limit."""
    clk = Clock()
    b = TokenBucket(rate=0, clock=clk)
    assert b.rate == 0.0
    assert b.try_take(1) > 0.0
    clk.t += 1e6
    assert b.try_take(1) > 0.0            # never refills
    store = ErrorStore()
    c = AdmissionController("S", rate_limit=0, policy="shed",
                            error_store=store, clock=clk,
                            now_ms=lambda: 1)
    assert c.offer(work(n=5)).action == SHED
    m = c.metrics()
    assert m["events_in"] == m["shed_events"] == 5
    assert m["admitted_events"] == 0
    assert len(store) == 1


def test_feed_safely_captures_failed_feed():
    """A feed whose closure does not self-capture (queued REST work
    drained by the scheduler pump) must still land in the ErrorStore
    on failure — admitted work never vanishes."""
    store = ErrorStore()
    c = AdmissionController("S", error_store=store, clock=Clock(),
                            now_ms=lambda: 7)

    def boom():
        raise RuntimeError("pipe burst")

    c.feed_safely(Work(n=2, nbytes=10, feed=boom,
                       rows=lambda: [(1, ("a",)), (2, ("b",))],
                       stream_id="S"))
    assert len(store) == 1
    ent = store.entries("S")[0]
    assert ent.point == "net.feed" and len(ent.events) == 2
    assert "pipe burst" in ent.message


def test_scheduler_pump_drains_queued_work_without_traffic():
    """'oldest'-policy work queued while the bucket was empty must be
    fed by the runtime scheduler pump once tokens refill, even when no
    further frame/REST traffic arrives to pump the controller."""
    import time as _time

    from siddhi_tpu import SiddhiManager
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "define stream S (x int);\n"
        "@info(name='q') from S select x insert into Out;\n")
    fed = []
    ctrl = AdmissionController("S", rate_limit=200, burst=1,
                               policy="oldest",
                               error_store=rt.error_store)
    rt.admission["S"] = ctrl
    rt.start()                      # real-time mode: scheduler pump runs
    try:
        w1 = work(n=1, fed=fed)
        assert ctrl.offer(w1).action == ADMIT
        w1.feed()                   # admitted work is fed by the CALLER
        assert ctrl.offer(work(n=1, fed=fed)).action == QUEUED
        deadline = _time.monotonic() + 5
        while _time.monotonic() < deadline and len(fed) < 2:
            _time.sleep(0.01)
        assert len(fed) == 2        # drained by the pump, no new offer
        assert ctrl.metrics()["pending_frames"] == 0
    finally:
        mgr.shutdown()
