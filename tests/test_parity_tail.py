"""Parity tail: per-group rate limiters, distributed sinks, ConfigManager
SPI, createSet/sizeOfSet, statistics reporters."""
import time

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.config import InMemoryConfigManager
from siddhi_tpu.core.io import InMemoryBroker
from siddhi_tpu.core.stats import register_stats_reporter


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def test_group_by_per_event_first_rate(mgr):
    """`output first every 2 events` with group by limits PER GROUP
    (reference: GroupByPerEventOutputRateLimiter)."""
    rt = mgr.create_app_runtime("""
        define stream S (sym string, p double);
        @info(name='q') from S select sym, sum(p) as total group by sym
        output first every 2 events insert into O;
    """)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    rt.start()
    h = rt.input_handler("S")
    for sym, p in (("A", 1.0), ("B", 10.0), ("A", 2.0), ("B", 20.0),
                   ("A", 3.0), ("B", 30.0)):
        h.send((sym, p))
    rt.flush()
    # first of every 2 PER GROUP: A@1, B@10, A@3(3rd A), B@60(3rd B)
    a_rows = [r for r in out if r[0] == "A"]
    b_rows = [r for r in out if r[0] == "B"]
    assert len(a_rows) == 2 and len(b_rows) == 2, out
    assert a_rows[0] == ("A", 1.0) and b_rows[0] == ("B", 10.0)


def test_group_by_last_rate(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (sym string, p double);
        @info(name='q') from S select sym, p group by sym
        output last every 2 events insert into O;
    """)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    rt.start()
    h = rt.input_handler("S")
    for sym, p in (("A", 1.0), ("A", 2.0), ("B", 5.0), ("B", 6.0)):
        h.send((sym, p))
    rt.flush()
    assert sorted(out) == [("A", 2.0), ("B", 6.0)]


def _broker_topics(topics):
    got = {t: [] for t in topics}
    subs = []
    for t in topics:
        fn = InMemoryBroker.subscribe(t, lambda m, _t=t: got[_t].append(m))
        subs.append((t, fn))
    return got, subs


def test_distributed_sink_round_robin(mgr):
    got, subs = _broker_topics(["d1", "d2"])
    rt = mgr.create_app_runtime("""
        define stream A (x int);
        @sink(type='inMemory', @map(type='passThrough'),
              @distribution(strategy='roundRobin',
                            @destination(topic='d1'),
                            @destination(topic='d2')))
        define stream B (x int);
        @info(name='q') from A select x insert into B;
    """)
    rt.start()
    h = rt.input_handler("A")
    for i in range(4):
        h.send((i,))
    rt.flush()
    assert got["d1"] == [(0,), (2,)] and got["d2"] == [(1,), (3,)]
    for t, fn in subs:
        InMemoryBroker.unsubscribe(t, fn)


def test_distributed_sink_broadcast_and_partitioned(mgr):
    got, subs = _broker_topics(["b1", "b2", "p1", "p2"])
    rt = mgr.create_app_runtime("""
        define stream A (sym string, x int);
        @sink(type='inMemory', @map(type='passThrough'),
              @distribution(strategy='broadcast',
                            @destination(topic='b1'),
                            @destination(topic='b2')))
        @sink(type='inMemory', @map(type='passThrough'),
              @distribution(strategy='partitioned', partitionKey='sym',
                            @destination(topic='p1'),
                            @destination(topic='p2')))
        define stream B (sym string, x int);
        @info(name='q') from A select sym, x insert into B;
    """)
    rt.start()
    h = rt.input_handler("A")
    for sym, x in (("K1", 1), ("K2", 2), ("K1", 3)):
        h.send((sym, x))
    rt.flush()
    assert got["b1"] == got["b2"] == [("K1", 1), ("K2", 2), ("K1", 3)]
    # partitioned: same key always lands on the same destination
    all_p = got["p1"] + got["p2"]
    assert sorted(all_p) == [("K1", 1), ("K1", 3), ("K2", 2)]
    k1_dest = ["p1" if ("K1", 1) in got["p1"] else "p2"]
    assert (("K1", 3) in got[k1_dest[0]])
    for t, fn in subs:
        InMemoryBroker.unsubscribe(t, fn)


def test_config_manager_spi(mgr):
    mgr.set_config_manager(InMemoryConfigManager({
        "source.inmemory.buffer": "99",
        "global_flag": "on",
        "sink.log.prefix": "XX",
    }))
    rt = mgr.create_app_runtime("""
        @source(type='inMemory', topic='cfg-t', @map(type='passThrough'))
        define stream S (x int);
        @info(name='q') from S select x insert into O;
    """)
    rt.start()
    src = rt.sources[0]
    assert src.config.read("buffer") == "99"
    assert src.config.read("global_flag") == "on"
    assert src.config.read("prefix") is None        # other namespace
    assert src.config.read("missing", "dflt") == "dflt"


def test_create_set_size_of_set(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (sym string, v int);
        @info(name='q') from S#window.lengthBatch(3)
        select sizeOfSet(unionSet(createSet(sym))) as distinct_syms
        insert into O;
    """)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    rt.start()
    h = rt.input_handler("S")
    for sym in ("A", "B", "A"):
        h.send((sym, 1))
    rt.flush()
    # running union per arriving event: {A}=1, {A,B}=2, {A,B,A}=2
    assert out == [(1,), (2,), (2,)]


def test_statistics_reporter_interval(mgr):
    seen = []
    register_stats_reporter("testrep", lambda app, rep: seen.append(rep))
    rt = mgr.create_app_runtime("""
        @app:statistics(reporter='testrep', interval='50 ms')
        define stream S (x int);
        @info(name='q') from S[x > 0] select x insert into O;
    """)
    rt.start()
    rt.input_handler("S").send((1,))
    rt.flush()
    time.sleep(0.25)
    rt.shutdown()
    assert len(seen) >= 2
    assert any(r["streams"].get("S", {}).get("events") == 1 for r in seen)
