"""`define function f[python] ...` script UDFs (reference:
core:function/Script.java:27, EvalScriptTestCase scenario shapes).
Round-3 VERDICT: definitions were parsed then silently dropped."""
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.planner import PlanError

HEAD = "define stream S (sym string, price double, vol int);\n"


def _run(app, rows):
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    out = []
    rt.add_callback("Out", lambda evs: out.extend(tuple(e.data) for e in evs))
    rt.start()
    h = rt.input_handler("S")
    for r in rows:
        h.send(r, timestamp=1000)
    rt.flush()
    m.shutdown()
    return out


def test_udf_expression_body_in_selector():
    app = HEAD + (
        "define function spread[python] return double { data[0] - data[1] };\n"
        "@info(name='q') from S select sym, spread(price, vol) as sp "
        "insert into Out;\n")
    out = _run(app, [("A", 10.5, 3), ("B", 2.0, 5)])
    assert out == [("A", 7.5), ("B", -3.0)]


def test_udf_statement_body_and_filter():
    app = HEAD + (
        "define function tier[python] return string {\n"
        "  if data[0] > 100.0:\n"
        "    return 'high'\n"
        "  return 'low'\n"
        "};\n"
        "@info(name='q') from S[tier(price) == 'high'] "
        "select sym, tier(price) as t insert into Out;\n")
    out = _run(app, [("A", 150.0, 1), ("B", 50.0, 1), ("C", 101.0, 1)])
    assert out == [("A", "high"), ("C", "high")]


def test_udf_return_type_coercion():
    app = HEAD + (
        "define function half[python] return int { data[0] / 2 };\n"
        "@info(name='q') from S select half(vol) as h insert into Out;\n")
    out = _run(app, [("A", 1.0, 9)])
    assert out == [(4,)]        # coerced to declared int


def test_udf_in_pattern_filter_falls_back_to_host():
    app = ("@app:devicePatterns('prefer')\n" + HEAD +
           "define function big[python] return bool { data[0] > 100.0 };\n"
           "@info(name='q') from every e1=S[big(price)] -> "
           "e2=S[price > e1.price] within 1 sec "
           "select e1.price as a, e2.price as b insert into Out;\n")
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    out = []
    rt.add_callback("Out", lambda evs: out.extend(tuple(e.data) for e in evs))
    rt.start()
    h = rt.input_handler("S")
    ts = 1_700_000_000_000
    for i, r in enumerate([("A", 150.0, 1), ("A", 160.0, 1), ("A", 50.0, 1)]):
        h.send(r, timestamp=ts + i)
    rt.flush()
    m.shutdown()
    assert out == [(150.0, 160.0)]


def test_unsupported_language_raises_at_build():
    app = HEAD + (
        "define function f[javascript] return int { return 1; };\n"
        "@info(name='q') from S select f(vol) as x insert into Out;\n")
    with pytest.raises(PlanError, match="javascript"):
        SiddhiManager().create_app_runtime(app)


def test_bad_python_body_raises_at_build():
    app = HEAD + (
        "define function f[python] return int { def broken( };\n"
        "@info(name='q') from S select f(vol) as x insert into Out;\n")
    with pytest.raises(PlanError, match="does not compile"):
        SiddhiManager().create_app_runtime(app)


def test_udf_in_store_query():
    app = HEAD + (
        "define function dbl[python] return double { data[0] * 2 };\n"
        "define table T (sym string, price double);\n"
        "@info(name='ins') from S select sym, price insert into T;\n")
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    rt.start()
    rt.input_handler("S").send(("A", 21.0, 1), timestamp=1000)
    rt.flush()
    rows = rt.query("from T select sym, dbl(price) as d;")
    m.shutdown()
    assert [r for _ts, r in rows] == [("A", 42.0)]


def test_udf_in_partition_clone():
    """Partition clones compile lazily (first event per key) — UDFs must
    still resolve there (r4 review finding)."""
    app = (HEAD +
           "define function boost[python] return double { data[0] + 1.0 };\n"
           "partition with (sym of S) begin\n"
           "@info(name='q') from S select sym, boost(price) as b "
           "insert into Out;\nend;\n")
    out = _run(app, [("A", 1.0, 1), ("B", 2.0, 1)])
    assert sorted(out) == [("A", 2.0), ("B", 3.0)]


def test_scripts_disabled_manager_rejects_app():
    """allow_scripts=False rejects [python] UDF apps at build time (advisor
    r4: script bodies execute with full interpreter privileges — the flag
    is the opt-out for untrusted app text)."""
    import pytest
    from siddhi_tpu.core.build import PlanError
    m = SiddhiManager(allow_scripts=False)
    app = (HEAD +
           "define function dbl[python] return double { data[0] * 2 };\n"
           "from S select dbl(price) as d insert into Out;\n")
    with pytest.raises(PlanError, match="allow_scripts"):
        m.create_app_runtime(app)
    # script-free apps still build fine on the same manager
    rt = m.create_app_runtime(HEAD + "from S select price insert into Out;\n")
    assert rt is not None
    m.shutdown()
