"""Scenario matrices ported (shapes, not code) from the reference's
pattern/sequence suites: siddhi-core/src/test/java/.../query/pattern/
absent/{AbsentPatternTestCase,LogicalAbsentPatternTestCase,
EveryAbsentPatternTestCase}.java and .../query/sequence/
SequenceTestCase.java (VERDICT r3 #8).

Every case runs BOTH engines — device ('prefer': device where the
kernel supports the shape, host fallback otherwise) and the host
matcher — and asserts identical outputs, plus an explicit expectation
where the reference scenario pins one (n matches / no match)."""
import pytest

from siddhi_tpu import SiddhiManager

DEVP = "@app:devicePatterns('prefer')\n"
HOST = "@app:devicePatterns('never')\n"

HEAD4 = """
@app:playback
define stream S1 (sym string, price double);
define stream S2 (sym string, price double);
define stream S3 (sym string, price double);
define stream S4 (sym string, price double);
"""

T0 = 1_000_000


def _run(app, sends, set_times=()):
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    out = []
    rt.add_callback("O", lambda evs: out.extend(
        tuple(None if v is None else v for v in e.data) for e in evs))
    rt.start()
    rt.set_time(T0 - 1)     # anchor: absent wait-clocks start at app start
    handlers = {}
    events = sorted(sends, key=lambda s: s[2])
    marks = sorted(set_times)
    mi = 0
    for sid, row, ts in events:
        while mi < len(marks) and marks[mi] <= ts:
            rt.set_time(marks[mi]); mi += 1
        h = handlers.get(sid) or handlers.setdefault(
            sid, rt.input_handler(sid))
        h.send(row, timestamp=ts)
        rt.flush()
    for t in marks[mi:]:
        rt.set_time(t)
    rt.flush()
    m.shutdown()
    return out


def both(body, sends, set_times=()):
    dev = _run(DEVP + HEAD4 + body, sends, set_times)
    host = _run(HOST + HEAD4 + body, sends, set_times)
    assert dev == host, (len(dev), len(host), dev[:4], host[:4])
    return dev


# ---------------------------------------------------------------------------
# AbsentPatternTestCase shapes: A -> not B for 1 sec (and permutations)
# ---------------------------------------------------------------------------

AB = ("from e1=S1[price>20] -> not S2[price>e1.price] for 1 sec "
      "select e1.sym as s1 insert into O;")
NOT_HEAD = ("from not S1[price>20] for 1 sec -> e2=S2[price>30] "
            "select e2.sym as s2 insert into O;")
CHAIN_NOT_TAIL = ("from e1=S1[price>10] -> e2=S2[price>20] -> "
                  "not S3[price>30] for 1 sec "
                  "select e1.sym as a, e2.sym as b insert into O;")
NOT_MID = ("from e1=S1[price>10] -> not S2[price>20] for 1 sec -> "
           "e3=S3[price>30] select e1.sym as a, e3.sym as b insert into O;")
NOT_HEAD_CHAIN = ("from not S1[price>10] for 1 sec -> e2=S2[price>20] -> "
                  "e3=S3[price>30] "
                  "select e2.sym as a, e3.sym as b insert into O;")
FOUR_NOT_TAIL = ("from e1=S1[price>10] -> e2=S2[price>20] -> "
                 "e3=S3[price>30] -> not S4[price>40] for 1 sec "
                 "select e1.sym as a, e3.sym as c insert into O;")
NOT_MID4 = ("from e1=S1[price>10] -> e2=S2[price>20] -> "
            "not S3[price>30] for 1 sec -> e4=S4[price>40] "
            "select e1.sym as a, e4.sym as d insert into O;")

ABSENT_CASES = {
    # e1 -> not e2: no e2 arrives -> match at deadline
    "tail_quiet": (AB, [("S1", ("A", 25.0), T0)], [T0 + 1100], 1),
    # e2 arrives after the deadline: still a match
    "tail_late_e2": (AB, [("S1", ("A", 25.0), T0),
                          ("S2", ("B", 30.0), T0 + 1200)], [T0 + 1100], 1),
    # e2 inside the window kills
    "tail_e2_inside": (AB, [("S1", ("A", 25.0), T0),
                            ("S2", ("B", 30.0), T0 + 500)], [T0 + 1100], 0),
    # e2 inside but filter unsatisfied (price <= e1.price): match
    "tail_e2_nofilter": (AB, [("S1", ("A", 25.0), T0),
                              ("S2", ("B", 20.0), T0 + 500)],
                         [T0 + 1100], 1),
    # not-head: quiet first second then e2 -> match
    "head_quiet_then_e2": (NOT_HEAD, [("S2", ("B", 35.0), T0 + 1200)],
                           [T0 + 1100], 1),
    # not-head: e1 arrives inside the window -> kill, no match
    "head_e1_inside": (NOT_HEAD, [("S1", ("A", 25.0), T0 + 300),
                                  ("S2", ("B", 35.0), T0 + 1200)],
                       [T0 + 1100], 0),
    # not-head: e2 arrives BEFORE the wait elapses -> no match for it
    "head_e2_early": (NOT_HEAD, [("S2", ("B", 35.0), T0 + 300)],
                      [T0 + 1100], 0),
    # chain with absent tail: e3 never arrives -> match
    "chain_tail_quiet": (CHAIN_NOT_TAIL,
                         [("S1", ("A", 15.0), T0),
                          ("S2", ("B", 25.0), T0 + 100)], [T0 + 1300], 1),
    # chain with absent tail: e3 arrives in window -> killed
    "chain_tail_e3": (CHAIN_NOT_TAIL,
                      [("S1", ("A", 15.0), T0),
                       ("S2", ("B", 25.0), T0 + 100),
                       ("S3", ("C", 35.0), T0 + 600)], [T0 + 1300], 0),
    # chain with absent tail: e3 fails its filter -> match
    "chain_tail_e3_nofilter": (CHAIN_NOT_TAIL,
                               [("S1", ("A", 15.0), T0),
                                ("S2", ("B", 25.0), T0 + 100),
                                ("S3", ("C", 29.0), T0 + 600)],
                               [T0 + 1300], 1),
    # absent mid-chain: quiet window then e3 -> match
    "mid_quiet": (NOT_MID, [("S1", ("A", 15.0), T0),
                            ("S3", ("C", 35.0), T0 + 1200)],
                  [T0 + 1100], 1),
    # absent mid-chain: e2 inside window -> killed
    "mid_e2": (NOT_MID, [("S1", ("A", 15.0), T0),
                         ("S2", ("B", 25.0), T0 + 400),
                         ("S3", ("C", 35.0), T0 + 1200)], [T0 + 1100], 0),
    # absent mid-chain: e2 fails filter -> match survives
    "mid_e2_nofilter": (NOT_MID, [("S1", ("A", 15.0), T0),
                                  ("S2", ("B", 19.0), T0 + 400),
                                  ("S3", ("C", 35.0), T0 + 1200)],
                        [T0 + 1100], 1),
    # not-head then 2-chain
    "head_chain": (NOT_HEAD_CHAIN, [("S2", ("B", 25.0), T0 + 1200),
                                    ("S3", ("C", 35.0), T0 + 1300)],
                   [T0 + 1100], 1),
    "head_chain_killed": (NOT_HEAD_CHAIN,
                          [("S1", ("A", 15.0), T0 + 200),
                           ("S2", ("B", 25.0), T0 + 1200),
                           ("S3", ("C", 35.0), T0 + 1300)], [T0 + 1100], 0),
    # 4-chain with absent tail
    "four_tail_quiet": (FOUR_NOT_TAIL,
                        [("S1", ("A", 15.0), T0),
                         ("S2", ("B", 25.0), T0 + 100),
                         ("S3", ("C", 35.0), T0 + 200)], [T0 + 1400], 1),
    "four_tail_e4": (FOUR_NOT_TAIL,
                     [("S1", ("A", 15.0), T0),
                      ("S2", ("B", 25.0), T0 + 100),
                      ("S3", ("C", 35.0), T0 + 200),
                      ("S4", ("D", 45.0), T0 + 700)], [T0 + 1400], 0),
    # absent mid in a 4-chain
    "mid4_quiet": (NOT_MID4,
                   [("S1", ("A", 15.0), T0),
                    ("S2", ("B", 25.0), T0 + 100),
                    ("S4", ("D", 45.0), T0 + 1300)], [T0 + 1200], 1),
    "mid4_e3": (NOT_MID4,
                [("S1", ("A", 15.0), T0),
                 ("S2", ("B", 25.0), T0 + 100),
                 ("S3", ("C", 35.0), T0 + 500),
                 ("S4", ("D", 45.0), T0 + 1300)], [T0 + 1200], 0),
}


@pytest.mark.parametrize("name", list(ABSENT_CASES))
def test_absent_matrix(name):
    body, sends, ticks, expected = ABSENT_CASES[name]
    out = both(body, sends, ticks)
    assert len(out) == expected, (name, out)


# ---------------------------------------------------------------------------
# LogicalAbsentPatternTestCase shapes: not-X and/or Y combinations
# ---------------------------------------------------------------------------

NOT_AND = ("from e1=S1[price>10] -> not S2[price>20] and e3=S3[price>30] "
           "select e1.sym as a, e3.sym as c insert into O;")
NOT_AND_HEAD = ("from not S1[price>10] and e2=S2[price>20] -> "
                "e3=S3[price>30] select e2.sym as b, e3.sym as c "
                "insert into O;")
NOT_FOR_AND = ("from e1=S1[price>10] -> not S2[price>20] for 1 sec and "
               "e3=S3[price>30] select e1.sym as a insert into O;")
NOT_FOR_OR = ("from e1=S1[price>10] -> not S2[price>20] for 1 sec or "
              "e3=S3[price>30] select e1.sym as a, e3.sym as c "
              "insert into O;")

LOGICAL_ABSENT_CASES = {
    # e1 then e3 (no e2): and-with-absent completes on e3
    "and_quiet": (NOT_AND, [("S1", ("A", 15.0), T0),
                            ("S3", ("C", 35.0), T0 + 300)], [], 1),
    # e2 arrives first: pair killed
    "and_e2": (NOT_AND, [("S1", ("A", 15.0), T0),
                         ("S2", ("B", 25.0), T0 + 100),
                         ("S3", ("C", 35.0), T0 + 300)], [], 0),
    # not-head and: e2 then e3 (no e1)
    "and_head_quiet": (NOT_AND_HEAD, [("S2", ("B", 25.0), T0),
                                      ("S3", ("C", 35.0), T0 + 300)],
                       [], 1),
    "and_head_e1": (NOT_AND_HEAD, [("S1", ("A", 15.0), T0 - 10),
                                   ("S2", ("B", 25.0), T0),
                                   ("S3", ("C", 35.0), T0 + 300)], [], 0),
    # not..for AND e3: e3 within window + quiet e2 -> match at deadline
    "for_and_quiet": (NOT_FOR_AND, [("S1", ("A", 15.0), T0),
                                    ("S3", ("C", 35.0), T0 + 400)],
                      [T0 + 1100], 1),
    # e2 inside window kills even though e3 matched
    "for_and_e2": (NOT_FOR_AND, [("S1", ("A", 15.0), T0),
                                 ("S2", ("B", 25.0), T0 + 200),
                                 ("S3", ("C", 35.0), T0 + 400)],
                   [T0 + 1100], 0),
    # not..for OR e3: e3 arrives -> immediate match (or-side)
    "for_or_e3": (NOT_FOR_OR, [("S1", ("A", 15.0), T0),
                               ("S3", ("C", 35.0), T0 + 400)],
                  [T0 + 1100], 1),
    # only the quiet second passes -> absent side fires (e3 NULL)
    "for_or_quiet": (NOT_FOR_OR, [("S1", ("A", 15.0), T0)],
                     [T0 + 1100], 1),
    # e2 arrives: absent side disarmed; no e3 -> nothing
    "for_or_e2_only": (NOT_FOR_OR, [("S1", ("A", 15.0), T0),
                                    ("S2", ("B", 25.0), T0 + 200)],
                       [T0 + 1100], 0),
    # e2 arrives but e3 later still completes the or
    "for_or_e2_then_e3": (NOT_FOR_OR, [("S1", ("A", 15.0), T0),
                                       ("S2", ("B", 25.0), T0 + 200),
                                       ("S3", ("C", 35.0), T0 + 500)],
                          [T0 + 1100], 1),
}


@pytest.mark.parametrize("name", list(LOGICAL_ABSENT_CASES))
def test_logical_absent_matrix(name):
    body, sends, ticks, expected = LOGICAL_ABSENT_CASES[name]
    out = both(body, sends, ticks)
    assert len(out) == expected, (name, out)


def test_for_or_quiet_emits_null_e3():
    out = both(NOT_FOR_OR, [("S1", ("A", 15.0), T0)], [T0 + 1100])
    assert out == [("A", None)]


# ---------------------------------------------------------------------------
# EveryAbsentPatternTestCase shapes: every + not combinations
# ---------------------------------------------------------------------------

EVERY_TAIL = ("from every e1=S1[price>20] -> not S2[price>e1.price] "
              "for 1 sec select e1.sym as a insert into O;")
EVERY_NOT_HEAD = ("from every not S1[price>10] for 1 sec -> "
                  "e2=S2[price>20] select e2.sym as b insert into O;")

EVERY_ABSENT_CASES = {
    # two e1 arms, both quiet -> two matches
    "every_two_arms": (EVERY_TAIL, [("S1", ("A", 25.0), T0),
                                    ("S1", ("B", 26.0), T0 + 200)],
                       [T0 + 1400], 2),
    # second arm killed by matching e2
    "every_one_killed": (EVERY_TAIL, [("S1", ("A", 25.0), T0),
                                      ("S1", ("B", 26.0), T0 + 200),
                                      ("S2", ("X", 26.5), T0 + 400)],
                         [T0 + 1400], 0),
    # e2 kills only arms whose filter it satisfies
    "every_filter_selective": (EVERY_TAIL,
                               [("S1", ("A", 30.0), T0),
                                ("S1", ("B", 26.0), T0 + 200),
                                ("S2", ("X", 27.0), T0 + 400)],
                               [T0 + 1400], 1),
    # every not-head: re-arms after each fire (2 quiet seconds, e2 then)
    "every_not_head": (EVERY_NOT_HEAD, [("S2", ("B", 25.0), T0 + 1200)],
                       [T0 + 1100], 1),
}


@pytest.mark.parametrize("name", list(EVERY_ABSENT_CASES))
def test_every_absent_matrix(name):
    body, sends, ticks, expected = EVERY_ABSENT_CASES[name]
    out = both(body, sends, ticks)
    assert len(out) == expected, (name, out)


# ---------------------------------------------------------------------------
# SequenceTestCase shapes (strict contiguity over the query's streams)
# ---------------------------------------------------------------------------

SEQ2 = ("from every e1=S1[price>20], e2=S1[price>e1.price] "
        "select e1.price as a, e2.price as b insert into O;")
SEQ3 = ("from every e1=S1[price>20], e2=S1[price>e1.price], "
        "e3=S1[price>e2.price] select e1.price as a, e3.price as c "
        "insert into O;")
SEQ_COUNT = ("from every e1=S1[price>20], e2=S1[price>20]<1:2>, "
             "e3=S1[price<10] select e1.price as a, e2[0].price as b, "
             "e3.price as c insert into O;")
SEQ_OR = ("from every e1=S1[price>20], e2=S1[price<5] or "
          "e3=S1[price>e1.price] select e1.price as a, e2.price as b, "
          "e3.price as c insert into O;")

SEQUENCE_CASES = {
    # contiguous pair matches
    "pair": (SEQ2, [("S1", ("A", 25.0), T0), ("S1", ("A", 26.0), T0 + 1)],
             1),
    # an intervening non-advancing event breaks strictness
    "pair_broken": (SEQ2, [("S1", ("A", 25.0), T0),
                           ("S1", ("A", 10.0), T0 + 1),
                           ("S1", ("A", 26.0), T0 + 2)], 0),
    # 3-chain contiguous
    "triple": (SEQ3, [("S1", ("A", 25.0), T0), ("S1", ("A", 26.0), T0 + 1),
                      ("S1", ("A", 27.0), T0 + 2)], 1),
    "triple_broken_late": (SEQ3, [("S1", ("A", 25.0), T0),
                                  ("S1", ("A", 26.0), T0 + 1),
                                  ("S1", ("A", 9.0), T0 + 2),
                                  ("S1", ("A", 27.0), T0 + 3)], 0),
    # count inside a sequence: one or two mids then the closer
    "count_one_mid": (SEQ_COUNT, [("S1", ("A", 25.0), T0),
                                  ("S1", ("A", 26.0), T0 + 1),
                                  ("S1", ("A", 5.0), T0 + 2)], 1),
    # `every` restarts at 26 too: (25,[26,27],5) and (26,[27],5)
    "count_two_mid": (SEQ_COUNT, [("S1", ("A", 25.0), T0),
                                  ("S1", ("A", 26.0), T0 + 1),
                                  ("S1", ("A", 27.0), T0 + 2),
                                  ("S1", ("A", 5.0), T0 + 3)], 2),
    # or-side in a sequence
    "or_right": (SEQ_OR, [("S1", ("A", 25.0), T0),
                          ("S1", ("A", 26.0), T0 + 1)], 1),
    "or_left": (SEQ_OR, [("S1", ("A", 25.0), T0),
                         ("S1", ("A", 2.0), T0 + 1)], 1),
}


@pytest.mark.parametrize("name", list(SEQUENCE_CASES))
def test_sequence_matrix(name):
    body, sends, expected = SEQUENCE_CASES[name]
    out = both(body, sends)
    assert len(out) == expected, (name, out)


def test_sequence_every_restarts():
    # every sequence: overlapping contiguous pairs each match
    sends = [("S1", ("A", 25.0), T0), ("S1", ("A", 26.0), T0 + 1),
             ("S1", ("A", 27.0), T0 + 2)]
    out = both(SEQ2, sends)
    assert out == [(25.0, 26.0), (26.0, 27.0)]
