"""Adaptive execution geometry (core/autotune.py): tuning-cache
persistence, planner consult, the AIMD SLO controller, geometry-
invariance differentials per device plan family, and the service/
telemetry surfacing."""
import json
import os
import time

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.autotune import (Autotuner, Geometry, SLOController,
                                      TuningCache, lint_path,
                                      plan_signature, shared_cache,
                                      signature_of, validate_cache_data)


def q4(x):
    return np.round(np.asarray(x) * 4) / 4


def tape(n, keys=8, seed=0, dt_ms=25):
    rng = np.random.default_rng(seed)
    return ({"sym": np.asarray([f"K{i}" for i in
                                rng.integers(0, keys, n)]),
             "p": q4(rng.uniform(90.0, 130.0, n)),
             "v": rng.integers(1, 100, n).astype(np.int32)},
            1_700_000_000_000 + np.arange(n, dtype=np.int64) * dt_ms)


def run_geometry(app, feeds, batch, depth=None, chunk_lanes=None,
                 capacity_switch=None):
    """Feed `feeds` ({stream: (cols, ts)}) in fixed cross-stream quanta,
    sub-chunked at `batch`, applying depth/chunk_lanes via the
    regeometry hook; returns the full decoded output row/ts sequence.
    `capacity_switch=(at_quantum, new_batch)` exercises a mid-stream
    SLO-controller decision (_apply_batch_target)."""
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    for p in rt._plans:
        rg = getattr(p, "regeometry", None)
        if rg is not None:
            rg(batch_hint=batch, depth=depth, chunk_lanes=chunk_lanes)
    out = []
    rt.add_batch_callback("Out", lambda b: out.extend(
        (int(ts), row) for ts, row in zip(b.timestamps,
                                          b.rows(rt.strings))))
    rt.start()
    handlers = {s: rt.input_handler(s) for s in feeds}
    Q = 128                     # fixed cross-stream interleave quantum
    n = min(len(ts) for _c, ts in feeds.values())
    for qi, q0 in enumerate(range(0, n, Q)):
        if capacity_switch is not None and qi == capacity_switch[0]:
            rt._apply_batch_target(capacity_switch[1])
            batch = capacity_switch[1]
        for s, (cols, ts) in feeds.items():
            hi_q = min(q0 + Q, n)
            for lo in range(q0, hi_q, batch):
                hi = min(lo + batch, hi_q)
                handlers[s].send_batch(
                    {k: v[lo:hi] for k, v in cols.items()}, ts[lo:hi])
    rt.flush()
    mgr.shutdown()
    return out


# ---------------------------------------------------------------------------
# geometry-invariance differentials: same tape, >= 3 geometries per plan
# family -> byte-identical outputs
# ---------------------------------------------------------------------------

FILTER_APP = """
define stream S (sym string, p double, v int);
@info(name='q') from S[p > 100] select sym, p, v * 2 as v2 insert into Out;
"""

WINDOW_APP = """
@app:deviceWindows('auto')
define stream S (sym string, p double, v int);
@info(name='q') from S#window.length(64)
select sym, sum(p) as sp, count() as c group by sym insert into Out;
"""

PATTERN_APP = """
@app:devicePatterns('prefer')
define stream S (sym string, p double, v int);
@info(name='q') from every e1=S[p > 100] -> e2=S[p > e1.p] within 1 sec
select e1.p as p1, e2.p as p2 insert into Out;
"""

JOIN_APP = """
define stream S (sym string, p double, v int);
define stream T (sym string, p double, v int);
@info(name='q') from S#window.length(32) as a join T#window.length(32) as b
on a.sym == b.sym and a.p > b.p
select a.sym as s, a.p as lp, b.p as rp insert into Out;
"""


@pytest.mark.parametrize("app,two_streams,geos", [
    (FILTER_APP, False, [(64, 0, None), (256, 2, None), (1024, 3, None)]),
    (WINDOW_APP, False, [(64, 0, None), (256, 2, None), (512, 3, None)]),
    (PATTERN_APP, False, [(128, 0, 8), (512, 2, 16), (1024, 3, 64)]),
    (JOIN_APP, True, [(32, 0, None), (64, 2, None), (128, 3, None)]),
], ids=["filter", "window", "pattern", "join"])
def test_geometry_invariance(app, two_streams, geos):
    n = 1024 if not two_streams else 512
    feeds = {"S": tape(n, seed=0)}
    if two_streams:
        feeds["T"] = tape(n, seed=1)
    ref = None
    for batch, depth, lanes in geos:
        out = run_geometry(app, feeds, batch, depth=depth,
                           chunk_lanes=lanes)
        assert out, f"geometry ({batch},{depth},{lanes}): no outputs"
        if ref is None:
            ref = out
        else:
            assert out == ref, (
                f"geometry ({batch},{depth},{lanes}) diverged: "
                f"{len(out)} vs {len(ref)} rows")


def test_regeometry_respects_can_pipeline():
    """A join with side filters must sync per flush (_can_pipeline is
    False): a tuner/controller depth hint never overrides that."""
    app = """
    define stream S (sym string, p double, v int);
    define stream T (sym string, p double, v int);
    from S[p > 100]#window.length(8) as a join T#window.length(8) as b
    on a.sym == b.sym select a.sym as s, b.p as bp insert into Out;
    """
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    plan = next(p for p in rt._plans
                if type(p).__name__ == "DeviceJoinPlan")
    assert not plan._can_pipeline and plan.pipeline_depth == 0
    plan.regeometry(batch_hint=512, depth=3)
    assert plan.pipeline_depth == 0 and plan._pipe.depth == 0
    assert plan.batch_hint == 512      # the safe knob still lands
    mgr.shutdown()


def test_controller_decision_is_output_invariant():
    """A mid-stream _apply_batch_target (what an SLO decision does at a
    flush boundary) must not change outputs."""
    feeds = {"S": tape(1024, seed=2)}
    ref = run_geometry(FILTER_APP, feeds, 128)
    switched = run_geometry(FILTER_APP, feeds, 128,
                            capacity_switch=(4, 512))
    assert switched == ref


# ---------------------------------------------------------------------------
# tuning cache: persistence round-trip, corruption fallback, lint
# ---------------------------------------------------------------------------

def test_cache_round_trip(tmp_path):
    path = str(tmp_path / "tuning.json")
    c1 = TuningCache(path)
    sig = signature_of("filter", "some-query-shape")
    assert c1.get(sig) is None and c1.misses == 1
    key = c1.put(sig, {"batch": 4096, "pipeline_depth": 2},
                 family="filter", score={"eps": 1000, "p99_ms": 3.2})
    assert "|" in key and os.path.exists(path)
    # a FRESH instance (new process analog) reads the same winner back
    c2 = TuningCache(path)
    ent = c2.get(sig)
    assert ent["geometry"] == {"batch": 4096, "pipeline_depth": 2}
    assert ent["family"] == "filter" and c2.hits == 1
    ok, msgs = lint_path(path)
    assert ok, msgs


def test_cache_corruption_falls_back(tmp_path):
    path = str(tmp_path / "tuning.json")
    with open(path, "w") as f:
        f.write("{ not json at all")
    with pytest.warns(RuntimeWarning, match="corrupt"):
        c = TuningCache(path)
        assert c.get(signature_of("filter", "x")) is None
    assert c.corrupt
    assert os.path.exists(path + ".corrupt")   # quarantined, not trusted
    # the cache still WORKS after corruption: a put() re-creates a valid
    # file (deploy is never bricked)
    sig = signature_of("window", "y")
    c.put(sig, {"batch": 1024})
    ok, msgs = lint_path(path)
    assert ok, msgs
    assert TuningCache(path).get(sig)["geometry"] == {"batch": 1024}


def test_cache_schema_lint_catches_malformed(tmp_path):
    bad = {"version": 1, "entries": {
        "sig|cpu|jax1": {"geometry": {"batch": "huge"}},
        "sig2|cpu|jax1": {"geometry": {"warp_factor": 9}},
        "sig3|cpu|jax1": {"geometry": {}}}}
    assert len(validate_cache_data(bad)) == 3
    assert validate_cache_data({"version": 99, "entries": {}})
    assert validate_cache_data([1, 2, 3])
    path = str(tmp_path / "t.json")
    with open(path, "w") as f:
        json.dump(bad, f)
    ok, msgs = lint_path(path)
    assert not ok and len(msgs) == 3
    # missing file = cold cache = fine
    ok, _ = lint_path(str(tmp_path / "nope.json"))
    assert ok


# ---------------------------------------------------------------------------
# the AIMD SLO controller
# ---------------------------------------------------------------------------

def drive(c, rate_eps, seconds, clock):
    """Virtual-clock closed loop: per-batch latency = fixed floor + time
    to fill the controller's batch target at the offered rate."""
    end = clock + seconds
    while clock < end:
        latency = 0.002 + c.batch_target / rate_eps
        clock += latency
        c.observe(latency)
        c.maybe_decide(clock)
    return clock


def test_aimd_convergence_under_rate_step():
    c = SLOController(target_s=0.025, initial_batch=4096, min_batch=32,
                      decide_every_s=0.25, min_samples=4)
    clock = drive(c, 100_000, 30.0, 0.0)
    # at 100k eps the sweet spot is batch ~2300 (0.023s fill): AIMD must
    # sit inside 2x target with a settled batch
    assert c.last_p99_s <= 2 * 0.025
    assert 1000 <= c.batch_target <= 2400
    settled = c.batch_target
    # STEP the offered rate down 5x: the old batch now takes ~115ms to
    # fill -> multiplicative decrease kicks in within a few windows
    clock = drive(c, 20_000, 30.0, clock)
    assert c.batch_target < settled / 2
    assert c.last_p99_s <= 2 * 0.025, \
        f"controller failed to re-converge: p99={c.last_p99_s * 1e3:.1f}ms"
    assert c.counts["decrease"] >= 1 and c.counts["increase"] >= 2
    # hysteresis: the band between target*(1-h) and target produces
    # hold decisions rather than oscillation
    assert c.counts["hold"] >= 1
    # decision log is telemetry-visible and bounded
    m = c.metrics()
    assert m["decision_log"] and m["decisions"]["decrease"] >= 1
    assert all(d["action"] in ("increase", "decrease", "hold")
               for d in m["decision_log"])
    # step back UP: additive increase recovers throughput
    before = c.batch_target
    drive(c, 100_000, 20.0, clock)
    assert c.batch_target > before


def test_controller_bounds_and_window_gating():
    c = SLOController(target_s=0.010, initial_batch=64, min_batch=32,
                      max_batch=128, decide_every_s=1.0, min_samples=4)
    # too few samples / too little elapsed time -> no decision
    c.maybe_decide(0.0)
    c.observe(0.5)
    assert c.maybe_decide(0.5) is None          # window not elapsed
    assert c.maybe_decide(2.0) is None          # min_samples not met
    for _ in range(4):
        c.observe(0.5)
    d = c.maybe_decide(3.0)
    assert d["action"] == "decrease" and c.batch_target == 32
    for _ in range(50):
        for _ in range(4):
            c.observe(0.0001)
        c.maybe_decide(c._last_decide + 2.0)
    assert c.batch_target == 128                # clamped at max_batch


# ---------------------------------------------------------------------------
# runtime wiring: @app:latencySLO + @app:maxBatchLatency fallback
# ---------------------------------------------------------------------------

def test_latency_slo_annotation_wires_controller():
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:latencySLO('25 ms')\n" + FILTER_APP)
    assert rt.slo is not None and rt.slo.adaptive
    assert rt.slo.target_s == pytest.approx(0.025)
    # flush cadence rides the controller: half the target by default
    assert rt.max_batch_latency_s == pytest.approx(0.0125)
    rt.start()
    cols, ts = tape(256, seed=3)
    rt.input_handler("S").send_batch(cols, ts)
    rt.flush()
    rep = rt.statistics()
    assert rep["slo"]["adaptive"] and rep["slo"]["target_ms"] == 25.0
    assert rep["slo"]["observed_batches"] >= 1
    # the controller's series render in the Prometheus exposition
    prom = rt.stats.prometheus()
    assert "siddhi_tpu_slo_batch_target" in prom
    assert "siddhi_tpu_slo_target_seconds" in prom
    mgr.shutdown()


def test_slo_oversize_batch_splits_output_invariant():
    """A columnar send far larger than the SLO batch target is split via
    the PR-4 halving machinery; outputs match the un-SLO'd run."""
    cols, ts = tape(2048, seed=4)

    def run(head):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(head + FILTER_APP)
        if head:
            rt._apply_batch_target(128)   # force 2048 >> 2 * target
        out = []
        rt.add_batch_callback("Out", lambda b: out.extend(
            (int(t), r) for t, r in zip(b.timestamps,
                                        b.rows(rt.strings))))
        rt.start()
        rt.input_handler("S").send_batch(cols, ts)
        rt.flush()
        mgr.shutdown()
        return out

    plain = run("")
    split = run("@app:latencySLO('25 ms')\n")
    assert split == plain and len(plain) > 0


def test_max_batch_latency_rides_controller_non_adaptive():
    """@app:maxBatchLatency reimplemented on the SLO controller path:
    cadence-only mode, no AIMD, and the auto-flush behavior holds (the
    no-silent-semantics-change fallback)."""
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:maxBatchLatency('40 ms')\n" + FILTER_APP)
    assert rt.slo is not None and not rt.slo.adaptive
    assert rt.slo.target_s is None
    assert rt.max_batch_latency_s == pytest.approx(0.040)
    # an aged-out partial builder still flushes without an explicit
    # flush() — the original annotation behavior
    got = []
    rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
    rt.start()
    h = rt.input_handler("S")
    h.send(("K1", 101.0, 1))        # far below batch_capacity
    deadline = time.time() + 5.0
    while not got and time.time() < deadline:
        time.sleep(0.01)
    mgr.shutdown()
    assert got == [("K1", 101.0, 2)]     # v2 = v * 2
    # and no controller decisions ever fire in cadence-only mode
    assert rt.slo.counts == {"increase": 0, "decrease": 0, "hold": 0}


def test_latency_cadence_drains_pipelined_results():
    """A depth-D dispatch pipeline (tuned or annotated) must not hold an
    aged-out micro-batch's results past the flush cadence: the scheduler
    pump drains in-flight entries, so latency targets and pipelining
    compose."""
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:maxBatchLatency('40 ms')\n@app:devicePipeline(2)\n"
        + FILTER_APP)
    assert rt._plans[0].pipeline_depth == 2
    got = []
    rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
    rt.start()
    rt.input_handler("S").send(("K1", 101.0, 1))
    deadline = time.time() + 5.0
    while not got and time.time() < deadline:
        time.sleep(0.01)     # NO explicit flush(): the pump must deliver
    mgr.shutdown()
    assert got == [("K1", 101.0, 2)]


# ---------------------------------------------------------------------------
# autotuner sweep + planner consult
# ---------------------------------------------------------------------------

def test_autotuner_sweep_persists_and_planner_consults(tmp_path,
                                                       monkeypatch):
    monkeypatch.setenv("SIDDHI_TUNE_CACHE", str(tmp_path / "tune.json"))
    tuner = Autotuner()     # the shared per-path cache runtimes consult
    res = tuner.tune(FILTER_APP, n_events=2048,
                     grid=[Geometry(batch=256, pipeline_depth=0),
                           Geometry(batch=512, pipeline_depth=2)],
                     warm_events=256)
    assert not res["from_cache"]
    assert len(res["candidates"]) == 2
    assert res["winner"]["batch"] in (256, 512)
    # every candidate saw identical outputs (enforced inside tune())
    ms = {c["matches"] for c in res["candidates"]}
    assert len(ms) == 1 and ms.pop() > 0
    # warm cache: the second tune() skips the sweep entirely
    res2 = tuner.tune(FILTER_APP, n_events=2048)
    assert res2["from_cache"] and res2["candidates"] == []
    # a fresh runtime build consults the persisted winner: batch
    # capacity + pipeline depth come from the cache, and the hit gauges
    # show it
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(FILTER_APP)
    assert rt.batch_capacity == res["winner"]["batch"]
    plan = rt._plans[0]
    assert plan.pipeline_depth == res["winner"]["pipeline_depth"]
    assert rt.tuner.hits >= 2
    rep_t = rt.statistics()["tuning"]
    assert rep_t["cache_hits"] >= 2 and rep_t["tuning_cache_entries"] >= 2
    prom = rt.stats.prometheus()
    assert "siddhi_tpu_tuning_cache_hits_total" in prom
    # explicit annotations still win over the cache
    rt2 = mgr.create_app_runtime("@app:devicePipeline(7)\n" + FILTER_APP)
    assert rt2._plans[0].pipeline_depth == 7
    mgr.shutdown()


def test_sweep_rejects_output_divergence(tmp_path):
    """The invariance guard actually fires: doctor one candidate's
    result path and the sweep must raise rather than persist."""
    from siddhi_tpu.core.autotune import AutotuneError
    tuner = Autotuner(TuningCache(str(tmp_path / "t.json")))
    real = tuner._measure
    calls = [0]

    def crooked(app_text, g, tapes, n_events, warm_events, out_streams):
        res = real(app_text, g, tapes, n_events, warm_events, out_streams)
        calls[0] += 1
        if calls[0] == 2:
            res["out_crc"] ^= 1
        return res

    tuner._measure = crooked
    with pytest.raises(AutotuneError, match="output-invariant"):
        tuner.tune(FILTER_APP, n_events=1024,
                   grid=[Geometry(batch=256), Geometry(batch=512)],
                   warm_events=256, force=True)


def test_plan_signature_stability():
    mgr = SiddhiManager()
    rt1 = mgr.create_app_runtime(FILTER_APP)
    rt2 = mgr.create_app_runtime(FILTER_APP)
    s1 = plan_signature(rt1._plans[0])
    assert s1 is not None and s1.startswith("filter:")
    assert s1 == plan_signature(rt2._plans[0])
    rt3 = mgr.create_app_runtime(FILTER_APP.replace("p > 100", "p > 99"))
    assert plan_signature(rt3._plans[0]) != s1
    mgr.shutdown()


# ---------------------------------------------------------------------------
# service surfacing
# ---------------------------------------------------------------------------

def test_service_tuning_endpoint():
    import urllib.request
    from siddhi_tpu.service import SiddhiService
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        app = ("@app:name('TuneMe')\n" + FILTER_APP)
        req = urllib.request.Request(f"{base}/siddhi/artifact/deploy",
                                     data=app.encode(), method="POST")
        assert json.loads(urllib.request.urlopen(req).read())["app"] \
            == "TuneMe"
        with urllib.request.urlopen(f"{base}/siddhi/artifact/tuning") as r:
            body = json.loads(r.read())
        assert body["path"] == shared_cache().path
        assert "entries" in body and "hits" in body and "device" in body
        with urllib.request.urlopen(
                f"{base}/siddhi/artifact/tuning?siddhiApp=TuneMe") as r:
            per_app = json.loads(r.read())
        assert per_app["app"] == "TuneMe"
        assert "cache_hits" in per_app and "cache_misses" in per_app
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{base}/siddhi/artifact/tuning?siddhiApp=Nope")
        assert ei.value.code == 404
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# fused-lane packing (@app:fusedLanes)
# ---------------------------------------------------------------------------

def test_fused_lane_packing_splits_groups():
    nq = 16     # MIN_GROUP is 8: a pack below it can't fuse on its own
    parts = ["@app:playback\n@app:fusedLanes(8)\n"
             "define stream S (sym string, p double);"]
    for i in range(nq):
        parts.append(
            f"@info(name='q{i}') from every e1=S[p > {100 + i}] -> "
            f"e2=S[p > e1.p] within 1 sec "
            f"select e1.p as p1, e2.p as p2 insert into Out{i};")
    app16 = "\n".join(parts)
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app16)
    fused = [p for p in rt._plans
             if type(p).__name__ == "MultiQueryDevicePatternPlan"]
    assert len(fused) == 2 and all(p.n_queries == 8 for p in fused)
    # unpacked: one kernel carries all 16 lanes
    rt2 = mgr.create_app_runtime(app16.replace("@app:fusedLanes(8)\n", ""))
    fused2 = [p for p in rt2._plans
              if type(p).__name__ == "MultiQueryDevicePatternPlan"]
    assert len(fused2) == 1 and fused2[0].n_queries == nq
    # same matches either way (lane packing is a geometry knob, not a
    # semantics knob)
    def feed(r):
        got = []
        for i in range(nq):
            r.add_callback(f"Out{i}", lambda evs, i=i: got.extend(
                (i, e.data) for e in evs))
        r.start()
        h = r.input_handler("S")
        rng = np.random.default_rng(7)
        ts0 = 1_700_000_000_000
        for k in range(256):
            h.send((f"K{k % 4}", float(q4(rng.uniform(90, 135)))),
                   timestamp=ts0 + k * 25)
        r.flush()
        return sorted(got)
    assert feed(rt) == feed(rt2)
    mgr.shutdown()
