"""End-to-end frame tracing (core/tracing.py + the wire TRACE frame):
cross-thread span trees must stay CONNECTED across every serving-path
hand-off (admission park/drain, WAL append, depth-D pipelined
materialization, sink retry after a breaker), egress frames must carry
the ingress trace id, traced and untraced runs must be byte-identical,
histogram buckets must carry OpenMetrics exemplars, and the whole
/metrics exposition must survive a text-format grammar check even with
hostile label values."""
import json
import os
import re
import tempfile
import time
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.telemetry import render_prometheus
from siddhi_tpu.core.tracing import FrameTracer
from siddhi_tpu.net import TcpFrameClient
from siddhi_tpu.net import frame as fp
from siddhi_tpu.net.client import FrameReceiver

STREAM_DEF = "define stream S (sym string, p double);\n"


def _cols(n, seed=0, lo=5.0, hi=15.0):
    r = np.random.default_rng(seed)
    return {"sym": np.array([f"K{i % 3}" for i in range(n)]),
            "p": np.round(r.uniform(lo, hi, n), 2)}


def _tree_check(spans):
    """Assert one connected tree: exactly one root, no orphans."""
    ids = {s["span"] for s in spans}
    roots = [s for s in spans if s["parent"] == 0]
    orphans = [s for s in spans
               if s["parent"] != 0 and s["parent"] not in ids]
    assert len(roots) == 1, f"expected one root, got {roots}"
    assert not orphans, f"orphan spans: {orphans}"
    return [s["name"] for s in spans]


# ---------------------------------------------------------------------------
# tentpole: one TCP-ingested frame on a durable app -> one connected tree
# ---------------------------------------------------------------------------

def test_e2e_tcp_durable_frame_trace_tree(tmp_path):
    recv = FrameReceiver()
    app = (f"@app:name('TraceE2E')\n"
           f"@app:trace('all')\n"
           f"@app:durability('batch', dir='{tmp_path}/wal')\n"
           f"@source(type='tcp', port='0')\n"
           + STREAM_DEF +
           "@info(name='q') from S[p > 10] select sym, p insert into Out;\n"
           f"@sink(type='tcp', host='127.0.0.1', port='{recv.port}')\n"
           "define stream Out (sym string, p double);\n")
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    rt.enable_stats(True)
    rt.start()
    try:
        cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "S",
                             TcpFrameClient.cols_of_schema(rt.schemas["S"]))
        cli.send_batch(_cols(8, lo=11.0, hi=20.0),
                       np.arange(8, dtype=np.int64), trace_id="prod-e2e-1")
        cli.barrier(timeout=60)
        rt.flush()
        cli.close()
        traces = rt.tracing.traces()
        assert "prod-e2e-1" in traces, sorted(traces)
        names = _tree_check(traces["prod-e2e-1"])
        # the causal chain the issue pins: admission -> wal.append ->
        # freeze -> device dispatch -> materialize -> sink egress
        for want in ("frame", "admit", "wal.append", "freeze",
                     "dispatch", "materialize", "sink.publish"):
            assert want in names, (want, names)
        # the wal.append span names the durable frame seq (trace rides
        # the WAL plane's per-stream frame identity)
        wal_span = next(s for s in traces["prod-e2e-1"]
                        if s["name"] == "wal.append")
        assert wal_span["args"]["seq"] == 1
        # the egress DATA frame re-stamped the INGRESS trace id
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                "prod-e2e-1" not in recv.trace_ids:
            time.sleep(0.02)
        assert "prod-e2e-1" in recv.trace_ids
    finally:
        rt.shutdown()
        recv.stop()


def test_traced_vs_untraced_outputs_byte_identical():
    body = (STREAM_DEF +
            "@info(name='q') from S#window.length(6) select sym, "
            "sum(p) as s insert into Out;\n")

    def run(head):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(head + body)
        rows = []
        rt.add_batch_callback("Out", lambda b: rows.extend(
            map(tuple, b.rows(rt.strings))))
        rt.start()
        h = rt.input_handler("S")
        for k in range(6):
            h.send_batch(_cols(16, seed=k), np.arange(16) + 16 * k)
            rt.flush()
        mgr.shutdown()
        return rows

    base = run("@app:trace('off')\n")
    traced = run("@app:trace('all')\n")
    assert base and traced == base


# ---------------------------------------------------------------------------
# cross-thread reparenting satellites
# ---------------------------------------------------------------------------

def test_depth4_pipelined_window_single_tree():
    """Depth-4 deferred materialization: the materialize span lands up
    to 4 batches later (and on flush) — every frame's tree must still
    be connected, with the materialize parented into ITS frame."""
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:trace('all')\n@app:deviceWindows('always')\n"
        "@app:devicePipeline(4)\n" + STREAM_DEF +
        "from S#window.length(6) select sym, sum(p) as s "
        "group by sym insert into O;\n")
    rt.start()
    h = rt.input_handler("S")
    for k in range(8):
        h.send_batch(_cols(8, seed=k), np.arange(8) + 8 * k)
    rt.flush()
    traces = rt.tracing.traces()
    mgr.shutdown()
    assert len(traces) == 8
    mat_threads = set()
    for tid, spans in traces.items():
        names = _tree_check(spans)
        assert "freeze" in names and "dispatch" in names
        assert "materialize" in names, (tid, names)
        mat_threads.update(s["thread"] for s in spans
                           if s["name"] == "materialize")
    assert mat_threads            # recorded, wherever they ran


def test_oldest_park_drain_lands_on_correct_parent():
    """'oldest'-policy admission: a parked frame drains later — often on
    the scheduler pump thread — and its freeze/dispatch spans must land
    on ITS tree, not the draining frame's."""
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:trace('all')\n"
        "@source(type='tcp', port='0', rate.limit='512', burst='64', "
        "shed.policy='oldest')\n" + STREAM_DEF +
        "@info(name='q') from S[p > 0] select sym, p insert into Out;\n")
    rt.start()
    try:
        cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "S",
                             TcpFrameClient.cols_of_schema(rt.schemas["S"]))
        for k in range(3):      # 64-event frames: the bucket admits the
            cli.send_batch(_cols(64, seed=k),   # first, the rest park
                           np.arange(64) + 64 * k,
                           trace_id=f"park-{k}")
        # without durability the ACK does not wait for the park: poll
        # until the scheduler pump drained + fed every parked frame
        deadline = time.monotonic() + 20
        traces = {}
        while time.monotonic() < deadline:
            traces = rt.tracing.traces()
            if all("freeze" in [s["name"] for s in traces.get(
                    f"park-{k}", [])] for k in range(3)):
                break
            time.sleep(0.05)
        rt.flush()
        traces = rt.tracing.traces()
        for k in range(3):
            tid = f"park-{k}"
            assert tid in traces, sorted(traces)
            names = _tree_check(traces[tid])
            for want in ("admit", "freeze", "dispatch"):
                assert want in names, (tid, names)
        cli.close()
    finally:
        rt.shutdown()


def test_sink_retry_after_breaker_stays_one_tree():
    """A sink publish that fails into an open breaker sheds the payload
    to the ErrorStore; the later replay re-publishes it.  The replayed
    publish span must resume the ORIGINAL frame's trace (the payload
    carries its resumable ctx) — one tree, no orphans."""
    recv = FrameReceiver(fail_first=2)
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:trace('all')\n" + STREAM_DEF +
        "@info(name='q') from S[p > 10] select sym, p insert into Out;\n"
        f"@sink(type='tcp', host='127.0.0.1', port='{recv.port}', "
        "on.error='store', max.retries='0', breaker.threshold='1', "
        "breaker.reset='50 ms')\n"
        "define stream Out (sym string, p double);\n")
    rt.start()
    try:
        h = rt.input_handler("S")
        h.send_batch(_cols(4, lo=11.0, hi=20.0), np.arange(4))
        rt.flush()
        # the publish failed (refused connection), payload stored
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not len(rt.error_store):
            time.sleep(0.02)
        assert len(rt.error_store) == 1
        time.sleep(0.1)               # breaker reset window
        out = rt.error_store.replay(rt)
        assert out["replayed"] == 1, out
        traces = rt.tracing.traces()
        assert len(traces) == 1
        spans = next(iter(traces.values()))
        names = _tree_check(spans)
        pubs = [s for s in spans if s["name"] == "sink.publish"]
        # the failed attempt AND the successful replay, same trace
        assert len(pubs) >= 2, names
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not recv.rows():
            time.sleep(0.02)
        assert recv.rows()
    finally:
        rt.shutdown()
        recv.stop()


# ---------------------------------------------------------------------------
# triggers + dumps
# ---------------------------------------------------------------------------

def test_slo_breach_trigger_exports_dump(tmp_path):
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        f"@app:trace('all', dir='{tmp_path}', cooldown='0')\n"
        "@app:latencySLO('0.01 ms')\n" + STREAM_DEF +
        "@info(name='q') from S[p > 10] select sym, p insert into Out;\n")
    rt.enable_stats(True)
    rt.start()
    try:
        h = rt.input_handler("S")
        deadline = time.monotonic() + 20
        k = 0
        files = []
        while time.monotonic() < deadline:
            h.send_batch(_cols(64, seed=k), np.arange(64) + 64 * k)
            rt.flush()
            k += 1
            time.sleep(0.01)
            files = [f for f in os.listdir(tmp_path)
                     if f.endswith(".json")]
            if files:
                break
        assert files, rt.tracing.metrics()
        obj = json.load(open(os.path.join(tmp_path, files[0])))
        # the Chrome object contract smoke.sh also lints
        assert "traceEvents" in obj and "metadata" in obj
        md = obj["metadata"]
        assert md["reason"] == "slo_breach"
        assert md["hostname"]                     # federation merge key
        assert md["app"] == rt.app.name
        # the dump's slowest span names the breaching stage
        assert md["slowest"]["name"] in (
            "admit", "wal.append", "freeze", "dispatch", "materialize",
            "sink.publish")
        assert rt.tracing.metrics()["triggers"].get("slo_breach")
        assert rt.tracing.dump_summaries()
    finally:
        rt.shutdown()


def test_trigger_cooldown_and_close():
    tr = FrameTracer("App", sample_every=1, cooldown_s=60.0)
    h = tr.begin_frame("S")
    h.mark("dispatch", time.perf_counter(), 0.001, plan="q")
    assert tr.trigger("quarantine", "plan q")
    assert not tr.trigger("quarantine", "again")      # cooldown
    assert tr.trigger("breaker_open", "other kind ok")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and len(tr.dumps) < 2:
        time.sleep(0.02)
    assert len(tr.dumps) == 2
    m = tr.metrics()
    assert m["triggers"] == {"quarantine": 1, "breaker_open": 1}
    assert m["triggers_suppressed"] == 1
    tr.close()
    assert not tr.trigger("quarantine", "after close")


def test_unsampled_frames_record_nothing():
    tr = FrameTracer("App", sample_every=0)       # sampling off
    assert tr.begin_frame("S") is None
    assert tr.begin_frame("S", trace_id="forced") is not None
    assert len(tr.traces()) == 1                  # producer id traced
    tr.close()


# ---------------------------------------------------------------------------
# wire TRACE frame
# ---------------------------------------------------------------------------

def test_trace_frame_codec_roundtrip():
    blob = fp.encode_trace("abc-1", 7)
    frames, rest = fp.parse_buffer(blob)
    assert not rest and frames[0][0] == fp.TRACE
    assert fp.decode_trace(frames[0][1]) == ("abc-1", 7)
    with pytest.raises(fp.FrameError):
        fp.decode_trace(b"{}")
    with pytest.raises(fp.FrameError):
        fp.decode_trace(b"not json")


# ---------------------------------------------------------------------------
# exemplars + exposition grammar (satellite: escaping round-trip)
# ---------------------------------------------------------------------------

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="(?:\\.|[^"\\\n])*"'
_VALUE = r"(?:NaN|[+-]Inf|-?[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?)"
_EXEMPLAR = rf" # \{{{_LABEL}(?:,{_LABEL})*\}} {_VALUE}(?: {_VALUE})?"
_SAMPLE_RE = re.compile(
    rf"^{_NAME}(?:\{{(?:{_LABEL}(?:,{_LABEL})*)?\}})? {_VALUE}"
    rf"(?:{_EXEMPLAR})?$")


def assert_valid_exposition(text: str) -> None:
    """Validate every line of a text exposition against the
    format grammar (names, escaped label values, numeric samples,
    optional OpenMetrics exemplar suffix)."""
    assert text.endswith("\n")
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# HELP ") or ln.startswith("# TYPE ") \
                or ln == "# EOF":
            continue
        assert _SAMPLE_RE.match(ln), f"bad exposition line: {ln!r}"


def _unescape(s: str) -> str:
    out, i = [], 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            out.append({"n": "\n", '"': '"', "\\": "\\"}
                       .get(s[i + 1], "\\" + s[i + 1]))
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def test_prometheus_label_escaping_roundtrip():
    """Hostile names (backslash, quote, newline) in app/stream/query
    labels AND exemplar trace ids must render as a grammar-valid
    exposition whose unescaped label values round-trip exactly."""
    evil_app = 'A"pp\\Ev\nil'
    evil_stream = 'S"tr\\eam\n1'
    evil_trace = 't"race\\id\n9'
    rep = {"uptime_s": 1.0,
           "streams": {evil_stream: {
               "events": 5, "batches": 2, "seconds": 0.1, "p50_ms": 1.0,
               "p95_ms": 2.0, "p99_ms": 3.0,
               "buckets": {"0.001": 1, "+Inf": 2},
               "exemplars": {"0.001": [evil_trace, 0.0005, 123.0]}}},
           "queries": {'q"u\\ery\n': {"events": 5, "batches": 1,
                                      "seconds": 0.05}},
           "stages": {}}
    text = render_prometheus({evil_app: rep}, openmetrics=True)
    assert_valid_exposition(text)
    # round-trip one sample line's labels back through unescape
    line = next(ln for ln in text.splitlines()
                if ln.startswith("siddhi_tpu_events_total{"))
    labs = dict(re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:\\.|[^"\\])*)"',
                           line))
    assert _unescape(labs["app"]) == evil_app
    assert _unescape(labs["stream"]) == evil_stream
    ex_line = next(ln for ln in text.splitlines() if " # {" in ln)
    ex_tid = re.search(r'# \{trace_id="((?:\\.|[^"\\])*)"\}', ex_line)
    assert ex_tid and _unescape(ex_tid.group(1)) == evil_trace


def test_live_exposition_grammar_and_exemplars():
    """A real traced runtime's full exposition parses against the
    grammar in BOTH formats; the OpenMetrics form carries a trace-id
    exemplar on at least one bucket, the classic 0.0.4 form carries
    NONE (exemplar syntax is illegal there — a real Prometheus parser
    would reject the whole exposition)."""
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:name('ExpoApp')\n@app:trace('all')\n" + STREAM_DEF +
        "@info(name='q') from S[p > 10] select sym, p insert into Out;\n")
    rt.enable_stats(True)
    rt.start()
    h = rt.input_handler("S")
    for k in range(3):
        h.send_batch(_cols(16, seed=k, lo=8.0, hi=20.0),
                     np.arange(16) + 16 * k)
        rt.flush()
    classic = rt.stats.prometheus()
    text = rt.stats.prometheus(openmetrics=True)
    mgr.shutdown()
    assert_valid_exposition(classic)
    assert_valid_exposition(text)
    # classic format: no exemplars, no EOF terminator
    assert not any(" # {" in ln for ln in classic.splitlines())
    assert "# EOF" not in classic
    assert text.rstrip().endswith("# EOF")
    bucket_lines = [ln for ln in text.splitlines() if ln.startswith(
        "siddhi_tpu_stream_dispatch_latency_seconds_bucket")]
    assert bucket_lines
    assert any(" # {" in ln and "trace_id=" in ln for ln in bucket_lines)
    assert "siddhi_tpu_trace_traces_total" in text
    # histogram invariants: cumulative buckets, +Inf == _count
    inf_line = next(ln for ln in bucket_lines if 'le="+Inf"' in ln)
    count_line = next(ln for ln in text.splitlines() if ln.startswith(
        "siddhi_tpu_stream_dispatch_latency_seconds_count{"))
    assert inf_line.split(" ")[1] == count_line.rsplit(" ", 1)[1]


# ---------------------------------------------------------------------------
# service surface
# ---------------------------------------------------------------------------

def test_service_trace_endpoint():
    from siddhi_tpu.service import SiddhiService
    svc = SiddhiService(port=0, net=True).start()
    try:
        base = f"http://127.0.0.1:{svc.port}"
        app = ("@app:name('TraceSvc')\n@app:trace('all')\n" + STREAM_DEF +
               "@info(name='q') from S[p > 10] select sym, p "
               "insert into Out;\n")
        req = urllib.request.Request(f"{base}/siddhi/artifact/deploy",
                                     data=app.encode(), method="POST")
        urllib.request.urlopen(req).read()
        cli = TcpFrameClient(
            "127.0.0.1", svc.net_port, "S",
            TcpFrameClient.cols_of_schema(svc.runtimes["TraceSvc"]
                                          .schemas["S"]),
            app="TraceSvc")
        cli.send_batch(_cols(4, lo=11.0, hi=20.0), np.arange(4),
                       trace_id="svc-trace-1")
        cli.barrier(timeout=60)
        cli.close()
        obj = json.loads(urllib.request.urlopen(
            f"{base}/siddhi/artifact/trace?siddhiApp=TraceSvc").read())
        assert "traceEvents" in obj and "metadata" in obj
        assert obj["metadata"]["hostname"]
        assert any(ev.get("args", {}).get("trace") == "svc-trace-1"
                   for ev in obj["traceEvents"] if ev.get("ph") == "X")
        # unknown app 404s
        try:
            urllib.request.urlopen(
                f"{base}/siddhi/artifact/trace?siddhiApp=Nope")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
        # content negotiation: an OpenMetrics scrape carries the
        # producer-stamped exemplar; the default (classic 0.0.4)
        # response must NOT (exemplar syntax is illegal there)
        req = urllib.request.Request(
            f"{base}/metrics",
            headers={"Accept": "application/openmetrics-text; "
                               "version=1.0.0"})
        with urllib.request.urlopen(req) as r:
            assert "openmetrics-text" in r.headers["Content-Type"]
            text = r.read().decode()
        assert 'trace_id="svc-trace-1"' in text
        assert_valid_exposition(text)
        with urllib.request.urlopen(f"{base}/metrics") as r:
            assert "version=0.0.4" in r.headers["Content-Type"]
            classic = r.read().decode()
        assert "trace_id=" not in classic
        assert_valid_exposition(classic)
    finally:
        svc.stop()


def test_tracer_reopens_on_restart_and_annotates_remote_parent():
    """(1) A shutdown()/start() cycle must re-arm the tracer — a closed
    tracer silently dropping every trigger after a restart would be the
    durability-silently-lost failure shape all over again.  (2) A wire
    TRACE frame's `span` field lands as the downstream root's
    `remote_parent` annotation (span ids are host-local)."""
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:trace('all', cooldown='0')\n"
        "@source(type='tcp', port='0')\n" + STREAM_DEF +
        "@info(name='q') from S[p > 0] select sym, p insert into Out;\n")
    rt.start()
    rt.shutdown()
    rt.start()
    try:
        assert rt.tracing.trigger("quarantine", "post-restart"), \
            "tracer stayed closed across shutdown()/start()"
        cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "S",
                             TcpFrameClient.cols_of_schema(rt.schemas["S"]))
        cli._send(fp.encode_trace("hop-1", 7))   # upstream head span 7
        cli.send_batch(_cols(4), np.arange(4))
        cli.barrier(timeout=60)
        cli.close()
        root = next(s for s in rt.tracing.traces()["hop-1"]
                    if s["name"] == "frame")
        assert root["parent"] == 0               # host-local root
        assert root["args"]["remote_parent"] == 7
    finally:
        rt.shutdown()
