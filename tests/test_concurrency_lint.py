"""Concurrency self-analysis (docs/ANALYSIS.md "Concurrency
self-analysis").

Five surfaces under test:
  * the rule groups (analysis/concurrency.py): a seeded-violation
    snippet corpus — at least two snippets per rule SL03–SL06 plus
    clean counterexamples that must stay silent;
  * the engine gate: `--threads` over siddhi_tpu/ itself exits 0 (the
    acceptance criterion), every suppression individually justified;
  * the CLI exit-code contract (0 clean / 1 findings / 2 usage) and
    `--expect` pinning for seeded corpora;
  * the runtime lock-witness (utils/locks.py, SIDDHI_LOCK_CHECK=1):
    real serving-plane traffic must exhibit zero acquisition orders the
    static lock graph contradicts or does not know;
  * mutation hardening: stripping ONE `with self._lock:` guard out of
    net/admission.py must trip SL03 — plus deterministic regression
    tests for the races this PR's triage fixed (concurrent same-name
    service deploys leaking a live runtime, double shutdown()).
"""
import ast
import json
import threading
import time

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.analysis.__main__ import main as cli_main
from siddhi_tpu.analysis.concurrency import (analyze_package,
                                             check_baseline, check_witness,
                                             lint_threads_source,
                                             suppression_inventory)
from siddhi_tpu.utils import locks as ulocks


def rule_ids(findings):
    return sorted(f.rule_id for f in findings)


# ---------------------------------------------------------------------------
# SL03 — lockset / inconsistent guard
# ---------------------------------------------------------------------------

SL03_UNGUARDED_READ = """
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0
    def bump(self):
        with self._lock:
            self.hits += 1
    def bump_again(self):
        with self._lock:
            self.hits += 1
    def scrape(self):
        return self.hits
"""

SL03_CONTAINER_WRITE = """
import threading
class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []
    def put(self, x):
        with self._lock:
            self.items.append(x)
    def size(self):
        with self._lock:
            return len(self.items)
    def sneak(self, x):
        self.items.append(x)
"""

SL03_CLEAN_GUARDED = """
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0
    def bump(self):
        with self._lock:
            self.hits += 1
    def scrape(self):
        with self._lock:
            return self.hits
"""

SL03_CLEAN_LOCKED_CONVENTION = SL03_UNGUARDED_READ.replace(
    "def scrape(self):", "def scrape_locked(self):")

SL03_CLEAN_PRAGMA = SL03_UNGUARDED_READ.replace(
    "        return self.hits",
    "        # lint: allow (atomic int read; scrape-only gauge)\n"
    "        return self.hits")


def test_sl03_unguarded_read_detected():
    fs = lint_threads_source(SL03_UNGUARDED_READ)
    assert rule_ids(fs) == ["SL03"]
    assert "self.hits" in fs[0].message and "scrape" in fs[0].message


def test_sl03_container_mutation_detected():
    fs = lint_threads_source(SL03_CONTAINER_WRITE)
    assert rule_ids(fs) == ["SL03"]
    assert "self.items" in fs[0].message and "sneak" in fs[0].message


def test_sl03_clean_counterexamples():
    assert lint_threads_source(SL03_CLEAN_GUARDED) == []
    assert lint_threads_source(SL03_CLEAN_LOCKED_CONVENTION) == []
    assert lint_threads_source(SL03_CLEAN_PRAGMA) == []
    # a class that owns no lock makes no locking promise
    no_lock = SL03_UNGUARDED_READ.replace(
        "        self._lock = threading.Lock()\n", "")
    assert lint_threads_source(no_lock) == []


def test_sl03_honors_legacy_unlocked_ok_pragma():
    legacy = SL03_UNGUARDED_READ.replace(
        "        return self.hits",
        "        return self.hits  # lint: unlocked-ok (single writer)")
    assert lint_threads_source(legacy) == []


def test_sl03_named_factory_locks_are_recognized():
    fs = lint_threads_source(SL03_UNGUARDED_READ.replace(
        "threading.Lock()", 'new_lock("C._lock")'))
    assert rule_ids(fs) == ["SL03"]


def test_sl03_locked_exemption_is_suffix_only():
    """`on_blocked` contains 'locked' but is NOT the caller-holds-lock
    convention — only the *_locked suffix exempts a method."""
    src = SL03_UNGUARDED_READ.replace("hits", "blocked_s").replace(
        "def scrape(self):", "def on_blocked(self):")
    assert rule_ids(lint_threads_source(src)) == ["SL03"]


def test_same_named_classes_in_different_modules_stay_separate():
    """Classes are keyed per module: a lock-free Worker in one file
    must not merge with (and corrupt the verdicts of) a lock-guarded
    Worker in another — in either direction."""
    from siddhi_tpu.analysis.concurrency import analyze_sources
    lockfree = "class Worker:\n    def run(self, x):\n"\
               "        self.items.append(x)\n"
    guarded = SL03_CONTAINER_WRITE.replace("class Q", "class Worker")
    both = analyze_sources([("a.py", lockfree), ("b.py", guarded)])
    alone = lint_threads_source(guarded, "b.py")
    assert [str(f) for f in both["findings"]] == [str(f) for f in alone]
    assert all("b.py" in (f.subject or "") for f in both["findings"])


# ---------------------------------------------------------------------------
# SL04 — lock-order inversion
# ---------------------------------------------------------------------------

SL04_CROSS_CLASS = """
import threading
class A:
    def __init__(self):
        self._lock = threading.Lock()
        self.b = B()
    def foo(self):
        with self._lock:
            self.b.bar()
class B:
    def __init__(self):
        self._lock = threading.Lock()
        self.a = None
    def bar(self):
        with self._lock:
            pass
    def baz(self):
        with self._lock:
            self.a.foo()
"""

SL04_SAME_CLASS = """
import threading
class D:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
    def one(self):
        with self._a:
            with self._b:
                pass
    def other(self):
        with self._b:
            with self._a:
                pass
"""

SL04_CLEAN_ORDER = """
import threading
class D:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
    def one(self):
        with self._a:
            with self._b:
                pass
    def two(self):
        with self._a:
            with self._b:
                pass
"""


def test_sl04_cross_class_inversion_detected():
    fs = lint_threads_source(SL04_CROSS_CLASS)
    assert "SL04" in rule_ids(fs)
    msg = next(f for f in fs if f.rule_id == "SL04").message
    assert "A._lock" in msg and "B._lock" in msg


def test_sl04_same_class_inversion_detected():
    fs = lint_threads_source(SL04_SAME_CLASS)
    assert rule_ids(fs) == ["SL04"]
    assert "D._a" in fs[0].message and "D._b" in fs[0].message


def test_sl04_consistent_order_is_clean():
    assert lint_threads_source(SL04_CLEAN_ORDER) == []


def test_sl04_annotated_edge_breaks_the_cycle_finding():
    annotated = SL04_SAME_CLASS.replace(
        "        with self._b:\n            with self._a:",
        "        with self._b:\n"
        "            # lint: allow (test-only: order proven unreachable)\n"
        "            with self._a:")
    assert lint_threads_source(annotated) == []


# ---------------------------------------------------------------------------
# SL05 — blocking call under a lock
# ---------------------------------------------------------------------------

SL05_SLEEP = """
import threading, time
class S:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
    def slow(self):
        with self._lock:
            time.sleep(0.5)
            self.n += 1
    def bump(self):
        with self._lock:
            self.n += 1
"""

SL05_SOCKET = """
import threading
class W:
    def __init__(self, sock):
        self._lock = threading.Lock()
        self.sock = sock
        self.sent = 0
    def send_all(self, data):
        with self._lock:
            self.sock.sendall(data)
            self.sent += 1
    def bump(self):
        with self._lock:
            self.sent += 1
"""

SL05_TRANSITIVE = """
import os, threading
class F:
    def __init__(self, f):
        self._lock = threading.Lock()
        self.f = f
        self.n = 0
    def _sync(self):
        os.fsync(self.f)
    def write(self):
        with self._lock:
            self._sync()
            self.n += 1
    def bump(self):
        with self._lock:
            self.n += 1
"""

SL05_CLEAN_OUTSIDE = """
import threading, time
class S:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
    def slow(self):
        time.sleep(0.5)
        with self._lock:
            self.n += 1
    def bump(self):
        with self._lock:
            self.n += 1
"""


def test_sl05_sleep_under_lock_detected():
    fs = lint_threads_source(SL05_SLEEP)
    assert rule_ids(fs) == ["SL05"]
    assert "time.sleep" in fs[0].message


def test_sl05_socket_send_under_lock_detected():
    fs = lint_threads_source(SL05_SOCKET)
    assert rule_ids(fs) == ["SL05"]
    assert "socket" in fs[0].message


def test_sl05_transitive_blocking_via_call_summary():
    fs = lint_threads_source(SL05_TRANSITIVE)
    assert rule_ids(fs) == ["SL05"]
    assert "os.fsync" in fs[0].message and "_sync" in fs[0].message


def test_sl05_clean_counterexample():
    assert lint_threads_source(SL05_CLEAN_OUTSIDE) == []


SL05_STDLIB_RECEIVER_CLEAN = """
import threading
import time
class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self.src = None
    def start(self):
        # a real blocking path behind the engine's own `start` name
        self.src.connect_with_retry()
    def connect_with_retry(self):
        time.sleep(0.1)
class Spawner:
    def __init__(self):
        self._lock = threading.Lock()
        self.worker = threading.Thread(name="siddhi-w", daemon=True)
    def kick(self):
        t = threading.Thread(target=self._run, name="siddhi-w",
                             daemon=True)
        with self._lock:
            self.worker = t
        t.start()
    def kick_attr(self):
        with self._lock:
            pass
        self.worker.start()
    def _run(self):
        pass
"""


def test_sl05_stdlib_receiver_does_not_alias_engine_methods():
    """`threading.Thread(...).start()` must NOT resolve onto an engine
    class's `start()` through the unique-method-name fallback: the
    stdlib-typed receiver is external, so spawning a thread near a lock
    cannot mint a false blocking chain through Engine.start's real
    time.sleep (the regression the tracing plane's trigger exporter
    surfaced)."""
    fs = lint_threads_source(SL05_STDLIB_RECEIVER_CLEAN)
    assert [f.rule_id for f in fs if f.rule_id == "SL05"] == []


# ---------------------------------------------------------------------------
# SL06 — thread lifecycle
# ---------------------------------------------------------------------------

SL06_LEAKY_UNNAMED = """
import threading
class T:
    def start(self):
        t = threading.Thread(target=self.run)
        t.start()
"""

SL06_BAD_NAME = """
import threading
class T:
    def start(self):
        t = threading.Thread(target=self.run, name="worker", daemon=True)
        t.start()
"""

SL06_COND_WAIT_NO_LOOP = """
import threading
class P:
    def __init__(self):
        self._cond = threading.Condition()
        self.ready = False
    def consume(self):
        with self._cond:
            if not self.ready:
                self._cond.wait()
    def produce(self):
        with self._cond:
            self.ready = True
            self._cond.notify()
"""

SL06_CLEAN = """
import threading
class T:
    def start(self):
        t = threading.Thread(target=self.run, name="siddhi-worker",
                             daemon=True)
        t.start()
"""

SL06_CLEAN_PREDICATE_LOOP = SL06_COND_WAIT_NO_LOOP.replace(
    "            if not self.ready:\n                self._cond.wait()",
    "            while not self.ready:\n                self._cond.wait()")


def test_sl06_leaky_unnamed_thread_detected():
    fs = lint_threads_source(SL06_LEAKY_UNNAMED)
    assert rule_ids(fs) == ["SL06"]
    assert "unnamed" in fs[0].message and "daemon" in fs[0].message


def test_sl06_non_siddhi_name_detected():
    fs = lint_threads_source(SL06_BAD_NAME)
    assert rule_ids(fs) == ["SL06"]
    assert "'worker'" in fs[0].message


def test_sl06_condition_wait_outside_predicate_loop():
    fs = lint_threads_source(SL06_COND_WAIT_NO_LOOP)
    assert rule_ids(fs) == ["SL06"]
    assert "predicate loop" in fs[0].message


def test_sl06_clean_counterexamples():
    assert lint_threads_source(SL06_CLEAN) == []
    assert lint_threads_source(SL06_CLEAN_PREDICATE_LOOP) == []
    # join-tracked non-daemon spawn is legitimate
    tracked = SL06_LEAKY_UNNAMED.replace(
        "        t = threading.Thread(target=self.run)",
        "        self._t = t = threading.Thread(target=self.run,\n"
        "                                       name='siddhi-worker')")
    tracked += ("    def stop(self):\n"
                "        self._t.join(timeout=5)\n")
    assert lint_threads_source(tracked) == []


def test_sl07_bare_pragma_is_itself_a_finding():
    bare = SL03_UNGUARDED_READ.replace(
        "        return self.hits",
        "        # lint: allow\n        return self.hits")
    assert "SL07" in rule_ids(lint_threads_source(bare))


def test_pragma_grammar_is_one_grammar():
    """Suppression, SL07, and the baseline inventory share ONE pragma
    grammar (walker.pragma_re): any spelling that suppresses is
    counted, and prose that is not a comment suppresses nothing."""
    from siddhi_tpu.analysis.walker import pragma_re
    rx = pragma_re("lint: allow")
    # no-space-before-paren suppresses...
    nospace = SL03_UNGUARDED_READ.replace(
        "        return self.hits",
        "        return self.hits  # lint: allow(single scraper)")
    assert lint_threads_source(nospace) == []
    # ...and the SAME regex the inventory counts with matches it
    assert rx.search("x  # lint: allow(single scraper)")
    # docstring/prose without a comment marker does NOT suppress
    prose = SL03_UNGUARDED_READ.replace(
        "    def scrape(self):",
        '    def scrape(self):\n        "see lint: allow (docs) note"')
    assert "SL03" in rule_ids(lint_threads_source(prose))
    assert not rx.search('"see lint: allow is documented elsewhere"')


# ---------------------------------------------------------------------------
# the engine gate (acceptance criteria)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine_analysis():
    """ONE whole-package analysis shared by the gate tests (each run
    costs ~5 s; the CLI package-mode test below keeps its own
    end-to-end invocation)."""
    return analyze_package()


def test_threads_package_is_clean(engine_analysis):
    """`python -m siddhi_tpu.analysis --threads` exits 0 over the
    engine source — the CI gate."""
    assert [str(f) for f in engine_analysis["findings"]] == []


def test_engine_lock_graph_shape(engine_analysis):
    g = engine_analysis["graph"]
    assert "SiddhiAppRuntime._lock" in g["nodes"]
    assert "AdmissionController._lock" in g["nodes"]
    edges = set(g["edges"])
    # the documented serving-plane orders must be in the model
    assert ("SiddhiAppRuntime._lock", "WriteAheadLog._lock") in edges
    assert ("SiddhiAppRuntime._net_gate", "SiddhiAppRuntime._lock") in edges
    assert ("AdmissionController._lock", "ErrorStore._lock") in edges


def test_every_engine_suppression_is_justified():
    """SL07 holds package-wide (part of the clean gate), and the
    inventory the baseline pins is non-trivial."""
    inv = suppression_inventory()
    assert sum(inv.values()) >= 10      # the triage wrote real pragmas
    assert all(n > 0 for n in inv.values())


def test_baseline_pin_detects_drift(tmp_path):
    inv = suppression_inventory()
    pin = tmp_path / "baseline.json"
    pin.write_text(json.dumps(inv))
    assert check_baseline(str(pin)) == []
    inv2 = dict(inv)
    inv2["net/admission.py"] = inv2.get("net/admission.py", 0) + 1
    pin.write_text(json.dumps(inv2))
    drift = check_baseline(str(pin))
    assert len(drift) == 1 and drift[0].rule_id == "SL-BASELINE"
    assert "net/admission.py" in drift[0].subject


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------

def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def test_cli_threads_package_mode_exits_zero(capsys):
    assert cli_main(["--threads"]) == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out


def test_cli_threads_seeded_corpus_exit_codes(tmp_path, capsys):
    race = _write(tmp_path, "race.py", SL03_UNGUARDED_READ)
    inv = _write(tmp_path, "inv.py", SL04_CROSS_CLASS)
    # findings -> 1
    assert cli_main(["--threads", race]) == 1
    # the two acceptance seeds: unguarded read AND lock-order inversion
    assert cli_main(["--threads", "--expect", "SL03,SL04",
                     race, inv]) == 0
    # drift from the pin -> 1
    assert cli_main(["--threads", "--expect", "SL03", race, inv]) == 1
    # usage: unreadable input -> 2
    assert cli_main(["--threads", str(tmp_path / "missing.py")]) == 2
    capsys.readouterr()


def test_cli_threads_json_shape(tmp_path, capsys):
    race = _write(tmp_path, "race.py", SL03_UNGUARDED_READ)
    assert cli_main(["--threads", "--json", race]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["findings"] == 1
    assert doc["threads"][0]["rule_id"] == "SL03"
    assert doc["threads"][0]["severity"] == "error"
    assert "C._lock" in doc["graph"]["nodes"]


def test_cli_gate_flags_require_threads(tmp_path, capsys):
    """--witness/--baseline silently ignored outside --threads would
    leave CI weaker than its author believes: usage error instead."""
    pin = tmp_path / "pin.json"
    pin.write_text("{}")
    assert cli_main(["--self", "--baseline", str(pin)]) == 2
    assert cli_main(["--self", "--witness", str(pin)]) == 2
    capsys.readouterr()


def test_cli_write_baseline_round_trip(tmp_path, capsys):
    pin = tmp_path / "pin.json"
    assert cli_main(["--threads", "--write-baseline", str(pin)]) == 0
    assert cli_main(["--threads", "--baseline", str(pin)]) == 0
    data = json.loads(pin.read_text())
    data["tests/fake.py"] = 2
    pin.write_text(json.dumps(data))
    assert cli_main(["--threads", "--baseline", str(pin)]) == 1
    capsys.readouterr()


# ---------------------------------------------------------------------------
# mutation hardening (acceptance: strip one guard from admission.py)
# ---------------------------------------------------------------------------

class _StripOneWith(ast.NodeTransformer):
    """Remove the first `with ...:` inside one named method, splicing
    its body into the enclosing scope."""

    def __init__(self, method):
        self.method = method
        self.in_target = False
        self.stripped = False

    def visit_FunctionDef(self, node):
        if node.name == self.method:
            self.in_target = True
            self.generic_visit(node)
            self.in_target = False
        return node

    def visit_With(self, node):
        self.generic_visit(node)
        if self.in_target and not self.stripped:
            self.stripped = True
            return node.body
        return node


def test_strip_one_guard_from_admission_is_caught():
    """Acceptance criterion: delete ONE `with self._lock:` from
    net/admission.py (pending_count's guard) and SL03 must flag the
    now-inconsistently-guarded attributes."""
    import siddhi_tpu.net.admission as admission
    src = open(admission.__file__, encoding="utf-8").read()
    assert lint_threads_source(src, "net/admission.py") == [], \
        "gate not green before mutation?"
    stripper = _StripOneWith("pending_count")
    tree = stripper.visit(ast.parse(src))
    assert stripper.stripped
    ast.fix_missing_locations(tree)
    findings = lint_threads_source(ast.unparse(tree), "net/admission.py")
    assert "SL03" in rule_ids(findings)
    flagged = " ".join(f.message for f in findings)
    assert "_pending" in flagged or "_inflight" in flagged


# ---------------------------------------------------------------------------
# runtime lock-witness (utils/locks.py)
# ---------------------------------------------------------------------------

@pytest.fixture
def witness_locks(monkeypatch):
    monkeypatch.setenv(ulocks.ENV_FLAG, "1")
    ulocks.witness().reset()
    yield ulocks.witness()
    ulocks.witness().reset()


def test_witness_records_acquisition_order(witness_locks):
    a = ulocks.new_lock("T.a")
    b = ulocks.new_lock("T.b")
    with a:
        with b:
            pass
    assert ("T.a", "T.b") in witness_locks.edges()
    assert {"T.a", "T.b"} <= witness_locks.locks()


def test_witness_trips_on_dynamic_inversion(witness_locks):
    a = ulocks.new_lock("T.a")
    b = ulocks.new_lock("T.b")
    with a:
        with b:
            pass
    with pytest.raises(ulocks.LockOrderError):
        with b:
            with a:
                pass


def test_witness_wrappers_mirror_the_lock_surface(witness_locks):
    lk = ulocks.new_lock("T.plain")
    assert lk.locked() is False
    with lk:
        assert lk.locked() is True
    rlk = ulocks.new_rlock("T.re")
    # RLock parity: no locked() (plain RLock has none either), but the
    # _is_owned runtime.flush() introspects is there
    assert not hasattr(threading.RLock(), "locked") or hasattr(rlk, "locked")
    assert rlk._is_owned() is False
    with rlk:
        assert rlk._is_owned() is True


def test_witness_merge_dump_is_concurrency_safe(witness_locks, tmp_path):
    """Two processes exiting together must not clobber each other's
    witness edges (a lost edge cannot fail the gate, so the loss would
    be invisible) — merge_dump serializes on an flock'd sidecar."""
    import subprocess
    import sys
    out = tmp_path / "w.json"
    code = (
        "import sys\n"
        "from siddhi_tpu.utils import locks as ul\n"
        "import os; os.environ[ul.ENV_FLAG] = '1'\n"
        "a = ul.new_lock('M.a%s')\n"
        "b = ul.new_lock('M.b%s')\n"
        "with a:\n"
        "    with b:\n"
        "        pass\n"
        "ul.witness().merge_dump(sys.argv[1])\n")
    procs = [subprocess.Popen([sys.executable, "-c", code % (i, i),
                               str(out)]) for i in range(3)]
    for p in procs:
        assert p.wait(timeout=60) == 0
    data = json.loads(out.read_text())
    for i in range(3):
        assert [f"M.a{i}", f"M.b{i}"] in data["edges"], data


def test_witness_disabled_returns_plain_locks(monkeypatch):
    monkeypatch.delenv(ulocks.ENV_FLAG, raising=False)
    lk = ulocks.new_lock("T.x")
    assert type(lk).__name__ != "_WitnessLockBase"
    with lk:
        pass
    assert "T.x" not in ulocks.witness().locks()


def test_witness_agrees_with_static_graph_on_real_traffic(witness_locks,
                                                          engine_analysis):
    """The acceptance agreement check, in-process: run real serving
    traffic (durable runtime + admission + shed + replay + snapshot +
    shutdown) under witness locks and assert ZERO witnessed acquisition
    orders the static graph contradicts or does not know."""
    from siddhi_tpu.net.admission import AdmissionController, Work
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime("""
        @app:name('WitnessAgree')
        define stream S (sym string, p double);
        @info(name='q') from S[p > 0] select sym, p insert into Out;
    """)
    rt.start()
    ctrl = AdmissionController("S", rate_limit=2.0, policy="shed",
                               burst=2.0, error_store=rt.error_store,
                               on_fault=rt.stats.on_fault,
                               now_ms=rt.now_ms)
    rt.admission["S"] = ctrl

    def feed():
        rt.send("S", ("A", 1.0))
        rt.flush()

    for _ in range(6):      # some admit, some shed into the ErrorStore
        ctrl.submit(Work(n=1, nbytes=32, feed=feed,
                         rows=lambda: [(0, ("A", 1.0))], stream_id="S"),
                    stop=lambda: True)
    rt.error_store.replay(rt)
    rt.snapshot()
    rt.shutdown()
    mgr.shutdown()

    g = engine_analysis["graph"]
    witness = witness_locks.to_dict()
    assert witness["edges"], "witness saw no nesting at all?"
    findings = check_witness(witness, g)
    assert [str(f) for f in findings] == []


def test_check_witness_flags_contradiction_and_unknown():
    graph = {"nodes": {"A", "B", "C"},
             "edges": {("A", "B"): ("x.py", 1, False)}}
    # reversed order -> contradiction
    fs = check_witness({"edges": [["B", "A"]]}, graph)
    assert len(fs) == 1 and "CONTRADICTS" in fs[0].message
    # order between known locks the model lacks -> unknown-edge failure
    fs = check_witness({"edges": [["A", "C"]]}, graph)
    assert len(fs) == 1 and "unknown to the static graph" in fs[0].message
    # a lock the model never inventoried
    fs = check_witness({"edges": [["A", "Z"]]}, graph)
    assert len(fs) == 1 and "never inventoried" in fs[0].message
    # a known path is fine
    assert check_witness({"edges": [["A", "B"]]}, graph) == []


# ---------------------------------------------------------------------------
# regression tests for the races the analyzer's triage fixed
# ---------------------------------------------------------------------------

APP = ("@app:name('RaceApp')\n"
       "define stream S (sym string, p double);\n"
       "@info(name='q') from S[p > 0] select sym, p insert into Out;\n")


def test_concurrent_same_name_deploys_leak_no_runtime():
    """Two deploys of the same name racing each other used to BOTH
    start a runtime; the loser leaked alive (scheduler thread running,
    never retired, never shut down).  Serialized deploys keep exactly
    one live runtime, and stop() reaps everything."""
    from siddhi_tpu.service import SiddhiService
    # only pumps spawned by THIS test count: earlier test files may
    # legitimately hold live runtimes of their own while we run
    before = {id(t) for t in threading.enumerate()}
    svc = SiddhiService(port=0, net=False).start()
    # query-less app: the race lives in the install/start/shutdown swap,
    # not the plan build — keep the builds cheap so the threads overlap
    race_app = "@app:name('RaceApp')\ndefine stream S (sym string);\n"
    try:
        errs = []

        def deploy():
            try:
                svc.deploy(race_app)
            except Exception as e:      # pragma: no cover
                errs.append(e)

        for _round in range(2):
            threads = [threading.Thread(target=deploy,
                                        name=f"siddhi-test-deploy-{i}")
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
        assert not errs
        assert list(svc.runtimes) == ["RaceApp"]
    finally:
        svc.stop()
    deadline = time.time() + 3.0
    while time.time() < deadline:
        pumps = [t for t in threading.enumerate()
                 if t.name == "siddhi-scheduler" and t.is_alive()
                 and id(t) not in before]
        if not pumps:
            break
        time.sleep(0.05)
    assert not pumps, (
        f"{len(pumps)} scheduler pump(s) survived service.stop() — a "
        f"deploy race leaked a live runtime")


def test_double_shutdown_is_serialized_and_idempotent():
    """shutdown() from two threads at once used to race the
    `self._sched_thread = None` hand-off (the loser joined None)."""
    for _ in range(5):
        mgr = SiddhiManager()
        rt = mgr.create_app_runtime(APP)
        rt.start()
        rt.send("S", ("A", 1.0))
        rt.flush()
        start = threading.Barrier(2)
        errs = []

        def down():
            try:
                start.wait(timeout=5)
                rt.shutdown()
            except Exception as e:
                errs.append(e)

        ts = [threading.Thread(target=down, name=f"siddhi-test-down-{i}")
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10)
        assert errs == []
        assert rt._sched_thread is None
        mgr.shutdown()


def test_netserver_stop_joins_threads_snapshotted_under_lock():
    """stop() used to read the connection-thread list outside the
    server lock while the accept loop rebuilt it; it now snapshots
    under the lock and joins every connection spawned before stop."""
    import socket as socketlib

    from siddhi_tpu.net.server import NetServer
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(APP)
    rt.start()
    srv = NetServer(lambda app, stream: (rt, None), port=0).start()
    socks = []
    try:
        for _ in range(8):
            s = socketlib.create_connection(("127.0.0.1", srv.port),
                                            timeout=5)
            socks.append(s)
        deadline = time.time() + 5.0
        while srv.open_connections < 8 and time.time() < deadline:
            time.sleep(0.01)
        assert srv.open_connections == 8
    finally:
        srv.stop()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        rt.shutdown()
        mgr.shutdown()
    leftovers = [t for t in threading.enumerate()
                 if t.name.startswith("siddhi-net-conn") and t.is_alive()]
    assert leftovers == []


def test_engine_threads_carry_siddhi_names():
    """Satellite: every thread a running engine spawns is named
    `siddhi-<role>` (SL06 holds this statically; this holds it live)."""
    before = {id(t) for t in threading.enumerate()}
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime("@app:async('true')\n" + APP)
    rt.start()
    rt.send("S", ("A", 1.0))
    rt.flush()
    spawned = [t for t in threading.enumerate() if id(t) not in before]
    assert spawned, "async runtime spawned no threads?"
    bad = [t.name for t in spawned if not t.name.startswith("siddhi-")]
    assert bad == []
    rt.shutdown()
    mgr.shutdown()
