"""File / incremental / async / periodic persistence (reference:
managment/PersistenceTestCase + IncrementalPersistenceTestCase,
IncrementalFileSystemPersistenceStore, AsyncSnapshotPersistor)."""
import time

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.persistence import (FileSystemPersistenceStore,
                                         IncrementalFileSystemPersistenceStore)

APP = """
define stream S (sym string, p double);
@PrimaryKey('sym')
define table T (sym string, p double);
@info(name='ins') from S select sym, p update or insert into T on T.sym == sym;
@info(name='w') from S#window.length(3) select sym, sum(p) as total
insert into O;
"""


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def _mk(mgr, store):
    mgr.set_persistence_store(store)
    rt = mgr.create_app_runtime(APP)
    rt.start()
    return rt


def test_file_store_roundtrip(mgr, tmp_path):
    store = FileSystemPersistenceStore(str(tmp_path))
    rt = _mk(mgr, store)
    h = rt.input_handler("S")
    h.send(("A", 1.0)); h.send(("B", 2.0))
    rt.flush()
    rev = rt.persist()
    assert store.last_revision(rt.app.name) == rev
    assert (tmp_path / rt.app.name).exists()

    m2 = SiddhiManager()
    m2.set_persistence_store(FileSystemPersistenceStore(str(tmp_path)))
    rt2 = m2.create_app_runtime(APP)
    rt2.restore_last_state()
    assert sorted(rt2.tables["T"].all_rows()) == [("A", 1.0), ("B", 2.0)]
    # window state carried over: next events continue the length-3 window
    out = []
    rt2.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    rt2.input_handler("S").send(("C", 4.0))
    rt2.flush()
    assert out[-1] == ("C", 7.0)    # 1 + 2 + 4
    m2.shutdown()


def test_incremental_store_chain(mgr, tmp_path):
    store = IncrementalFileSystemPersistenceStore(str(tmp_path))
    rt = _mk(mgr, store)
    h = rt.input_handler("S")
    h.send(("A", 1.0)); rt.flush()
    rt.persist(incremental=True)        # F- base
    h.send(("B", 2.0)); rt.flush()
    rt.persist(incremental=True)        # I- delta (op-log)
    h.send(("A", 9.0)); rt.flush()      # update-or-insert -> set op
    rt.persist(incremental=True)        # I- delta
    revs = store.revisions(rt.app.name)
    assert sum(r.startswith("F-") for r in revs) == 1
    assert sum(r.startswith("I-") for r in revs) == 2

    m2 = SiddhiManager()
    m2.set_persistence_store(
        IncrementalFileSystemPersistenceStore(str(tmp_path)))
    rt2 = m2.create_app_runtime(APP)
    rt2.restore_last_state()
    assert sorted(rt2.tables["T"].all_rows()) == [("A", 9.0), ("B", 2.0)]
    m2.shutdown()


def test_incremental_threshold_refull(mgr, tmp_path):
    store = IncrementalFileSystemPersistenceStore(str(tmp_path))
    rt = _mk(mgr, store)
    h = rt.input_handler("S")
    h.send(("A", 1.0)); rt.flush()
    rt.persist(incremental=True)
    # mutate far past 2.1x the live size -> next incremental re-fulls
    for i in range(200):
        h.send((f"K{i % 3}", float(i)))
    rt.flush()
    rt.persist(incremental=True)
    revs = store.revisions(rt.app.name)
    assert sum(r.startswith("F-") for r in revs) == 2


def test_async_persist(mgr, tmp_path):
    store = FileSystemPersistenceStore(str(tmp_path))
    rt = _mk(mgr, store)
    rt.input_handler("S").send(("A", 1.0))
    rt.flush()
    rev = rt.persist(asynchronous=True)
    rt.persistor().wait()
    assert rt.persistor().errors == []
    assert store.last_revision(rt.app.name) == rev


def test_periodic_persistence(mgr, tmp_path):
    store = FileSystemPersistenceStore(str(tmp_path))
    rt = _mk(mgr, store)
    rt.input_handler("S").send(("A", 1.0))
    rt.flush()
    handle = rt.persist_every(0.05)
    time.sleep(0.3)
    handle.stop()
    assert len(handle.revisions) >= 2 and handle.errors == []
    assert store.last_revision(rt.app.name) is not None
