"""Chunked-halo device NFA: differential equality against the host
matcher for within-bounded every-head patterns (the P=1 lane-starvation
fix — pattern_plan._run_chunked_flat).

The mode splits each flush into K own-chunks scanned by K parallel
lanes; halo reads + `__can_start__` head masking keep every match found
exactly once, and the replayed tail + completion-seq dedup keep
cross-flush continuity.  These tests drive MANY small flushes so the
replay path is exercised hard (reference semantics oracle:
interp/nfa.py; scenario shapes after
modules/siddhi-core/src/test/java/org/wso2/siddhi/core/query/pattern/)."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

HEAD = "define stream S (sym string, price double);\n@info(name='q') "

QUERIES = {
    "two_state": (
        "from every e1=S[price > 100] -> e2=S[price > e1.price] within 1 sec "
        "select e1.price as p1, e2.price as p2 insert into Out;"),
    "three_state": (
        "from every e1=S[price > 100] -> e2=S[price > e1.price] "
        "-> e3=S[price > e2.price] within 2 sec "
        "select e1.price as p1, e2.price as p2, e3.price as p3 "
        "insert into Out;"),
    "count": (
        "from every e1=S[price > 110]<1:3> -> e2=S[price < 95] within 1 sec "
        "select e1[0].price as a, e1[last].price as b, e2.price as c "
        "insert into Out;"),
    "logical_and": (
        "from every e1=S[price > 120] -> e2=S[price < 100] and "
        "e3=S[price > 125] within 1 sec "
        "select e1.price as a, e2.price as b, e3.price as c insert into Out;"),
    "logical_or": (
        "from every e1=S[price > 120] -> e2=S[price < 92] or "
        "e3=S[price > 127] within 1 sec "
        "select e1.price as a, e2.price as b, e3.price as c insert into Out;"),
    "sequence": (
        "from every e1=S[price > 115], e2=S[price > e1.price] within 1 sec "
        "select e1.price as a, e2.price as b insert into Out;"),
    "head_count": (
        "from every e1=S[price > 118]<2:4> within 1 sec "
        "select e1[0].price as a, e1[1].price as b insert into Out;"),
}


def _run(head, q, n=1800, batches=6, seed=11, dt=9):
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(head + HEAD + q)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(
        (e.timestamp,
         tuple(None if x is None else round(float(x), 3)
               if isinstance(x, float) else x for x in e.data))
        for e in evs))
    rt.start()
    plan = rt._plans[0]
    chunked = getattr(plan, "_chunk_cfg", None) is not None
    rng = np.random.default_rng(seed)
    ih = rt.input_handler("S")
    ts0 = 1_700_000_000_000
    for b in range(batches):
        for j in range(n // batches):
            i = b * (n // batches) + j
            ih.send((f"K{rng.integers(0, 4)}",
                     float(np.round(rng.uniform(90, 130) * 4) / 4)),
                    timestamp=ts0 + i * dt)
        rt.flush()
    mgr.shutdown()
    return chunked, rows


@pytest.mark.parametrize("name", [
    pytest.param(n, marks=pytest.mark.slow)
    if n in ("head_count", "count") else n
    for n in QUERIES])
def test_chunked_differential(name):
    # force the CHUNK family: since the ISSUE-13 eligibility expansion
    # the scan family would otherwise win these shapes by default, and
    # this file is chunk's own differential corpus
    q = QUERIES[name]
    chunked, dev = _run("@app:patternFamily('chunk')\n"
                        "@app:devicePatterns('always')\n", q)
    _h, host = _run("@app:devicePatterns('never')\n", q)
    assert chunked, f"{name}: chunked mode did not engage"
    assert dev == host, (name, len(dev), len(host),
                         list(set(dev) - set(host))[:3],
                         list(set(host) - set(dev))[:3])


def test_chunked_many_small_flushes():
    """Replay-tail dedup across dozens of tiny flushes (every flush
    overlaps the previous one's within-window)."""
    q = QUERIES["two_state"]
    chunked, dev = _run("@app:patternFamily('chunk')\n"
                        "@app:devicePatterns('always')\n", q,
                        n=900, batches=30, dt=25, seed=5)
    _h, host = _run("@app:devicePatterns('never')\n", q,
                    n=900, batches=30, dt=25, seed=5)
    assert chunked
    assert dev == host


def test_chunked_sparse_data_reduces_lanes():
    """Halo-dominated data (few events per within-window) still matches:
    the geometry collapses to fewer lanes rather than mis-matching."""
    q = QUERIES["two_state"]
    chunked, dev = _run("@app:patternFamily('chunk')\n"
                        "@app:devicePatterns('always')\n", q,
                        n=300, batches=3, dt=400, seed=7)
    _h, host = _run("@app:devicePatterns('never')\n", q,
                    n=300, batches=3, dt=400, seed=7)
    assert chunked
    assert dev == host


def test_chunked_lane_annotation_disable():
    """@app:deviceChunkLanes(0) turns the CHUNK family off.  Since the
    plan-family split, that no longer forces the threaded state path —
    the associative-scan family (which has no lane knob) may still
    engage; `@app:patternFamily('seq')` is the explicit opt-out."""
    from siddhi_tpu.core.pattern_plan import DevicePatternPlan
    q = QUERIES["two_state"]
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:devicePatterns('always')\n@app:deviceChunkLanes(0)\n"
        + HEAD + q)
    plan = next(p for p in rt._plans if isinstance(p, DevicePatternPlan))
    assert plan.families["chunk"] is not True     # lanes knob honored
    assert plan.family != "chunk"
    mgr.shutdown()
    _c, dev = _run(
        "@app:devicePatterns('always')\n@app:deviceChunkLanes(0)\n", q,
        n=600, batches=3)
    _h, host = _run("@app:devicePatterns('never')\n", q, n=600, batches=3)
    assert dev == host
    # the explicit sequential opt-out engages the threaded state path
    chunked, dev2 = _run(
        "@app:devicePatterns('always')\n@app:patternFamily('seq')\n", q,
        n=600, batches=3)
    assert not chunked
    assert dev2 == host


def test_chunked_snapshot_restore():
    """Snapshot carries the replay tail + dedup seq: restoring mid-stream
    neither loses nor duplicates matches."""
    app = ("@app:devicePatterns('always')\n" + HEAD + QUERIES["two_state"])
    rng = np.random.default_rng(3)
    tape = [(f"K{rng.integers(0, 3)}",
             float(np.round(rng.uniform(90, 130) * 4) / 4))
            for _ in range(600)]
    ts0 = 1_700_000_000_000

    def feed(rt, lo, hi):
        ih = rt.input_handler("S")
        for i in range(lo, hi):
            ih.send(tape[i], timestamp=ts0 + i * 9)
        rt.flush()

    # continuous run
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    ref = []
    rt.add_callback("Out", lambda evs: ref.extend(
        (e.timestamp, tuple(e.data)) for e in evs))
    rt.start()
    feed(rt, 0, 300)
    feed(rt, 300, 600)
    mgr.shutdown()

    # snapshot at 300, restore into a fresh runtime, continue
    mgr1 = SiddhiManager()
    rt1 = mgr1.create_app_runtime(app)
    got = []
    rt1.add_callback("Out", lambda evs: got.extend(
        (e.timestamp, tuple(e.data)) for e in evs))
    rt1.start()
    assert rt1._plans[0]._chunk_cfg is not None
    feed(rt1, 0, 300)
    snap = rt1.snapshot()
    mgr1.shutdown()

    mgr2 = SiddhiManager()
    rt2 = mgr2.create_app_runtime(app)
    rt2.add_callback("Out", lambda evs: got.extend(
        (e.timestamp, tuple(e.data)) for e in evs))
    rt2.start()
    rt2.restore(snap)
    feed(rt2, 300, 600)
    mgr2.shutdown()

    assert got == ref
