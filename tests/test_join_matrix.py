"""Join scenario matrix ported (shapes, not code) from the reference's
join suites: siddhi-core/src/test/java/.../query/join/JoinTestCase.java
and OuterJoinTestCase.java (VERDICT r4 #6).  Stream-stream cases run BOTH
engines (device join kernel where the shape lowers, host interp always)
and assert identical outputs plus the reference scenario's expectation."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

CSE = "define stream cseEventStream (symbol string, price double, volume int);\n"
TWT = "define stream twitterStream (user string, tweet string, company string);\n"
T0 = 1_000_000


def run(head, app, sends, out="outputStream", marks=()):
    m = SiddhiManager()
    rt = m.create_app_runtime(head + app)
    rows = []
    rt.add_callback(out, lambda evs: rows.extend(
        (e.timestamp, tuple(e.data)) for e in evs))
    rt.start()
    events = sorted(sends, key=lambda s: s[2])
    marks = sorted(marks)
    mi = 0
    for sid, row, ts in events:
        while mi < len(marks) and marks[mi] <= ts:
            rt.set_time(marks[mi]); mi += 1
        rt.send(sid, row, timestamp=ts)
        rt.flush()
    for t in marks[mi:]:
        rt.set_time(t)
    rt.flush()
    m.shutdown()
    return rows


def both(app, sends, out="outputStream", marks=(), head=""):
    dev = run(head, app, sends, out, marks)
    host = run(head + "@app:deviceJoins('never')\n", app, sends, out, marks)
    assert dev == host, (len(dev), len(host), dev[:4], host[:4])
    return dev


CSE_SENDS = [("cseEventStream", ("WSO2", 55.6, 100), T0),
             ("twitterStream", ("User1", "Hello World", "WSO2"), T0 + 10),
             ("cseEventStream", ("IBM", 75.6, 100), T0 + 20),
             ("cseEventStream", ("WSO2", 57.6, 100), T0 + 30)]


# -- JoinTestCase shapes ---------------------------------------------------

def test_join1_qualified_names():
    """joinTest1: unaliased stream-qualified join on length windows."""
    app = (CSE + TWT +
           "@info(name='query1') from cseEventStream#window.length(10) "
           "join twitterStream#window.length(10) "
           "on cseEventStream.symbol == twitterStream.company "
           "select cseEventStream.symbol as symbol, twitterStream.tweet, "
           "cseEventStream.price insert into outputStream;")
    out = both(app, CSE_SENDS)
    # WSO2 event joins the tweet when it arrives + the later WSO2 arrival
    assert len(out) == 2
    assert all(r[1][0] == "WSO2" for r in out)


def test_join2_aliased():
    """joinTest2: `as a join ... as b`."""
    app = (CSE + TWT +
           "@info(name='query1') from cseEventStream#window.length(10) as a "
           "join twitterStream#window.length(10) as b "
           "on a.symbol == b.company "
           "select a.symbol as symbol, b.tweet, a.price "
           "insert into outputStream;")
    assert len(both(app, CSE_SENDS)) == 2


def test_join3_self_join():
    """joinTest3: self-join of a stream on its own window."""
    app = (CSE +
           "@info(name='query1') from cseEventStream#window.length(5) as a "
           "join cseEventStream#window.length(5) as b "
           "on a.symbol == b.symbol "
           "select a.symbol as symbol, a.price as priceA, b.price as priceB "
           "insert into outputStream;")
    sends = [("cseEventStream", ("WSO2", 55.6, 100), T0),
             ("cseEventStream", ("WSO2", 57.6, 100), T0 + 10)]
    out = both(app, sends)
    # second WSO2 arrival: left-probe and right-probe each pair with the
    # retained first event
    assert len(out) == 2


def test_join5_cross_no_condition():
    """joinTest8-style: join with no on-condition (cross join)."""
    app = (CSE + TWT +
           "@info(name='query1') from cseEventStream#window.length(1) "
           "join twitterStream#window.length(1) "
           "select cseEventStream.symbol as symbol, tweet, price "
           "insert into outputStream;")
    out = both(app, CSE_SENDS)
    assert len(out) == 3     # tweet joins WSO2; IBM joins tweet; WSO2#2 joins


def test_join_windowless_both():
    """joinTest6/7: windowless sides retain nothing — no output."""
    app = (CSE + TWT +
           "@info(name='query1') from cseEventStream join twitterStream "
           "select cseEventStream.symbol as symbol, tweet "
           "insert into outputStream;")
    assert both(app, CSE_SENDS) == []


def test_join_unidirectional_windowless_trigger():
    """joinTest11: unidirectional windowless side triggers against the
    windowed side."""
    app = (CSE + TWT +
           "@info(name='query1') from cseEventStream unidirectional "
           "join twitterStream#window.length(1) "
           "select symbol, tweet insert into outputStream;")
    sends = [("twitterStream", ("User1", "Hi", "WSO2"), T0),
             ("cseEventStream", ("WSO2", 55.6, 100), T0 + 10),
             ("cseEventStream", ("IBM", 75.6, 100), T0 + 20)]
    out = both(app, sends)
    assert len(out) == 2     # each cse arrival pairs the retained tweet


def test_join_having_on_either_side():
    """joinTest14-17 family: having over either side's selected columns."""
    for having, expect_sym in ((("a.price > 56", "WSO2"),
                                ("b.company == 'WSO2'", "WSO2"))):
        app = (CSE + TWT +
               "@info(name='query1') from cseEventStream#window.length(10) "
               "as a join twitterStream#window.length(10) as b "
               "on a.symbol == b.company "
               f"select a.symbol as symbol, a.price as price having {having} "
               "insert into outputStream;")
        out = both(app, CSE_SENDS)
        assert all(r[1][0] == expect_sym for r in out)


def test_join_group_by_count():
    """joinTest10-style: aggregating selector over a join (host path)."""
    app = (CSE + TWT +
           "@info(name='query1') from cseEventStream#window.length(3) "
           "join twitterStream#window.length(3) "
           "on cseEventStream.symbol == twitterStream.company "
           "select cseEventStream.symbol as symbol, count() as events "
           "group by cseEventStream.symbol insert into outputStream;")
    out = both(app, CSE_SENDS)      # falls back to host on both runs
    assert out


# -- OuterJoinTestCase shapes ---------------------------------------------

OUTER_SENDS = [("cseEventStream", ("WSO2", 55.6, 100), T0),
               ("cseEventStream", ("IBM", 75.6, 100), T0 + 10),
               ("twitterStream", ("User1", "Hello World", "WSO2"), T0 + 20),
               ("cseEventStream", ("WSO2", 57.6, 100), T0 + 30)]


def test_outer_full():
    """outerJoinTest1: full outer join length(3) x length(1)."""
    app = (CSE + TWT +
           "@info(name='query1') from cseEventStream#window.length(3) "
           "full outer join twitterStream#window.length(1) "
           "on cseEventStream.symbol == twitterStream.company "
           "select cseEventStream.symbol as symbol, twitterStream.tweet, "
           "cseEventStream.price insert into outputStream;")
    out = both(app, OUTER_SENDS)
    # misses for WSO2/IBM before the tweet; joined rows after
    assert any(r[1][1] is None for r in out)
    assert any(r[1][1] == "Hello World" for r in out)


def test_outer_right():
    """outerJoinTest2: right outer join — tweet arrival emits even
    without a cse match."""
    app = (CSE + TWT +
           "@info(name='query1') from cseEventStream#window.length(1) "
           "right outer join twitterStream#window.length(2) "
           "on cseEventStream.symbol == twitterStream.company "
           "select twitterStream.tweet, cseEventStream.symbol as symbol "
           "insert into outputStream;")
    sends = [("twitterStream", ("User1", "no match yet", "GOOG"), T0)]
    out = both(app, sends)
    assert out == [(T0, ("no match yet", None))]


def test_outer_left():
    """outerJoinTest3: left outer join."""
    app = (CSE + TWT +
           "@info(name='query1') from cseEventStream#window.length(2) "
           "left outer join twitterStream#window.length(1) "
           "on cseEventStream.symbol == twitterStream.company "
           "select cseEventStream.symbol as symbol, twitterStream.tweet "
           "insert into outputStream;")
    out = both(app, OUTER_SENDS)
    assert out[0] == (T0, ("WSO2", None))       # miss before the tweet
    assert any(r[1] == ("WSO2", "Hello World") for r in out)


def test_outer_right_windowless_left():
    """outerJoinTest7: right outer with a windowless left side."""
    app = (CSE + TWT +
           "@info(name='query1') from cseEventStream#window.length(2) "
           "right outer join twitterStream "
           "on cseEventStream.symbol == twitterStream.company "
           "select cseEventStream.symbol as symbol, twitterStream.tweet "
           "insert into outputStream;")
    out = both(app, OUTER_SENDS)
    assert out     # tweet probes the cse window; cse arrivals never emit


def test_inner_keyword():
    """outerJoinTest8: explicit `inner join` keyword."""
    app = (CSE + TWT +
           "@info(name='query1') from cseEventStream#window.length(3) "
           "inner join twitterStream#window.length(1) "
           "on cseEventStream.symbol == twitterStream.company "
           "select cseEventStream.symbol as symbol, twitterStream.tweet "
           "insert into outputStream;")
    out = both(app, OUTER_SENDS)
    assert all(r[1][1] is not None for r in out)


# -- time-window joins (host engine; device falls back) -------------------

def test_join_time_windows_playback():
    """joinTest1's original time windows, on the event timeline."""
    app = ("@app:playback\n" + CSE + TWT +
           "@info(name='query1') from cseEventStream#window.time(1 sec) "
           "join twitterStream#window.time(1 sec) "
           "on cseEventStream.symbol == twitterStream.company "
           "select cseEventStream.symbol as symbol, twitterStream.tweet "
           "insert into outputStream;")
    out = both(app, CSE_SENDS, marks=(T0 + 2000,))
    assert len(out) == 2


# -- randomized differential over the matrix shapes -----------------------

@pytest.mark.parametrize("shape", [
    "from cseEventStream#window.length(4) as a join "
    "twitterStream#window.length(4) as b on a.symbol == b.company "
    "select a.symbol as s, b.tweet as t insert into outputStream;",
    "from cseEventStream#window.length(3) as a full outer join "
    "twitterStream#window.length(2) as b on a.symbol == b.company "
    "select a.symbol as s, b.tweet as t insert into outputStream;",
    "from cseEventStream#window.length(2) as a unidirectional join "
    "twitterStream#window.length(5) as b on a.symbol == b.company "
    "select a.symbol as s, b.user as u insert into outputStream;",
])
def test_join_matrix_fuzz(shape):
    rng = np.random.default_rng(hash(shape) % 2**32)
    syms = ["WSO2", "IBM", "GOOG"]
    sends = []
    for i in range(60):
        if rng.random() < 0.5:
            sends.append(("cseEventStream",
                          (syms[int(rng.integers(3))],
                           float(rng.integers(50, 90)), 100), T0 + i))
        else:
            sends.append(("twitterStream",
                          (f"U{i}", f"tweet{i}",
                           syms[int(rng.integers(3))]), T0 + i))
    both(CSE + TWT + "@info(name='q') " + shape, sends)
