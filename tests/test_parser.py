"""Front-end golden tests: SiddhiQL text -> AST structure.

Mirrors the reference's query-compiler test strategy (parse a string, assert
AST equivalence — reference: modules/siddhi-query-compiler/src/test/.../
SimpleQueryTestCase.java, PatternQueryTestCase.java etc.)."""
import pytest

from siddhi_tpu.query import ast, parse, parse_expression, parse_query
from siddhi_tpu.query.ast import AttrType, CompareOp, MathOp


def test_define_stream():
    app = parse("define stream StockStream (symbol string, price double, volume int);")
    sd = app.stream_definitions["StockStream"]
    assert sd.attributes == (
        ast.Attribute("symbol", AttrType.STRING),
        ast.Attribute("price", AttrType.DOUBLE),
        ast.Attribute("volume", AttrType.INT),
    )


def test_app_annotations_and_table():
    app = parse("""
        @app:name('Test')  @app:statistics(reporter='console')
        define stream S (a int);
        @PrimaryKey('k') @Index('v')
        define table T (k string, v int);
    """)
    assert app.name == "Test"
    td = app.table_definitions["T"]
    assert td.primary_keys() == ["k"]
    assert td.indexes() == ["v"]


def test_simple_filter_query():
    app = parse("""
        define stream StockStream (symbol string, price double, volume int);
        @info(name='q1')
        from StockStream[price > 100 and volume < 50] select symbol, price
        insert into OutStream;
    """)
    q = app.execution_elements[0]
    assert q.name("x") == "q1"
    inp = q.input
    assert isinstance(inp, ast.SingleInputStream)
    assert inp.stream_id == "StockStream"
    f = inp.filters[0].expr
    assert isinstance(f, ast.And)
    assert f.left == ast.Compare(ast.Variable("price"), CompareOp.GT,
                                 ast.Constant(100, AttrType.INT))
    assert q.selector.attributes[0].name == "symbol"
    assert isinstance(q.output, ast.InsertInto)
    assert q.output.target == "OutStream"


def test_window_query_with_groupby_having():
    q = parse_query("""
        from StockStream#window.length(20)
        select symbol, avg(price) as avgPrice
        group by symbol
        having avgPrice > 50
        insert all events into OutStream
    """)
    w = q.input.window
    assert w.name == "length"
    assert w.args == (ast.Constant(20, AttrType.INT),)
    assert q.selector.group_by == (ast.Variable("symbol"),)
    assert isinstance(q.selector.having, ast.Compare)
    assert q.output.events_for == ast.OutputEventsFor.ALL


def test_time_windows_and_units():
    q = parse_query("from S#window.time(1 min 30 sec) select * insert into O")
    assert q.input.window.args == (ast.TimeConstant(90_000),)
    q2 = parse_query("from S#window.timeBatch(500 ms) select * insert expired events into O")
    assert q2.input.window.args == (ast.TimeConstant(500),)
    assert q2.output.events_for == ast.OutputEventsFor.EXPIRED


def test_join_query():
    q = parse_query("""
        from TickStream#window.length(10) as t
        join NewsStream#window.time(5 sec) as n
        on t.symbol == n.symbol
        select t.symbol, t.price, n.headline
        insert into JoinedStream
    """)
    j = q.input
    assert isinstance(j, ast.JoinInputStream)
    assert j.left.ref_id == "t" and j.right.ref_id == "n"
    assert j.join_type == ast.JoinType.INNER
    assert isinstance(j.on, ast.Compare)


def test_left_outer_join():
    q = parse_query("""
        from A#window.length(5) left outer join B#window.length(5)
        on A.x == B.x select A.x insert into O
    """)
    assert q.input.join_type == ast.JoinType.LEFT_OUTER


def test_pattern_query():
    q = parse_query("""
        from every e1=StockStream[price > 100] -> e2=StockStream[price > e1.price]
        within 1 sec
        select e1.price as p1, e2.price as p2
        insert into AlertStream
    """)
    st = q.input
    assert isinstance(st, ast.StateInputStream)
    assert st.type == ast.StateType.PATTERN
    assert st.within == ast.TimeConstant(1000)
    nxt = st.state
    assert isinstance(nxt, ast.NextStateElement)
    assert isinstance(nxt.state, ast.EveryStateElement)
    e1 = nxt.state.state
    assert isinstance(e1, ast.StreamStateElement)
    assert e1.stream.ref_id == "e1"
    e2 = nxt.next
    assert isinstance(e2, ast.StreamStateElement)
    # cross-state reference e1.price
    f = e2.stream.filters[0].expr
    assert f.right == ast.Variable("price", stream_ref="e1")


def test_pattern_count_and_logical():
    q = parse_query("""
        from e1=A[x>1]<2:5> -> e2=B and e3=C -> not D[y==2] for 2 sec
        select e1[0].x as first insert into O
    """)
    s = q.input.state
    c = s.state
    assert isinstance(c, ast.CountStateElement)
    assert (c.min_count, c.max_count) == (2, 5)
    mid = s.next.state
    assert isinstance(mid, ast.LogicalStateElement) and mid.op == "and"
    absent = s.next.next
    assert isinstance(absent, ast.AbsentStreamStateElement)
    assert absent.waiting_time == ast.TimeConstant(2000)
    # indexed reference e1[0].x
    sel = q.selector.attributes[0].expr
    assert sel == ast.Variable("x", stream_ref="e1", index=0)


def test_sequence_query():
    q = parse_query("""
        from every e1=Stock[price>100], e2=Stock[price>e1.price]
        select e1.price, e2.price insert into O
    """)
    st = q.input
    assert st.type == ast.StateType.SEQUENCE
    assert isinstance(st.state, ast.NextStateElement)


def test_sequence_regex_counts():
    q = parse_query("from e1=A+, e2=B? select e1[0].x insert into O")
    st = q.input
    assert st.type == ast.StateType.SEQUENCE
    plus = st.state.state
    assert isinstance(plus, ast.CountStateElement)
    assert (plus.min_count, plus.max_count) == (1, ast.CountStateElement.ANY)
    opt = st.state.next
    assert (opt.min_count, opt.max_count) == (0, 1)


def test_partition():
    app = parse("""
        define stream S (symbol string, price double);
        partition with (symbol of S)
        begin
            @info(name='pq')
            from S select symbol, avg(price) as ap insert into #Inner;
            from #Inner select * insert into Out;
        end;
    """)
    p = app.execution_elements[0]
    assert isinstance(p, ast.Partition)
    assert p.keys[0].stream_id == "S"
    assert p.keys[0].expr == ast.Variable("symbol")
    assert len(p.queries) == 2
    assert p.queries[0].output.is_inner
    assert p.queries[1].input.is_inner


def test_range_partition():
    app = parse("""
        define stream S (p double);
        partition with (p < 10 as 'low' or p >= 10 as 'high' of S)
        begin from S select p insert into O; end;
    """)
    k = app.execution_elements[0].keys[0]
    assert len(k.ranges) == 2
    assert k.ranges[0].key == "low"


def test_output_rate():
    q = parse_query("from S select a output last every 5 events insert into O")
    assert q.rate == ast.EventOutputRate(5, ast.RateType.LAST)
    q2 = parse_query("from S select a output snapshot every 1 sec insert into O")
    assert q2.rate == ast.SnapshotOutputRate(1000)
    q3 = parse_query("from S select a output every 100 ms insert into O")
    assert q3.rate == ast.TimeOutputRate(100, ast.RateType.ALL)


def test_table_ops():
    q = parse_query("from S select sym, p update or insert into T set T.p = p on T.sym == sym")
    assert isinstance(q.output, ast.UpdateOrInsertTable)
    assert q.output.set_clauses[0].attribute == ast.Variable("p", stream_ref="T")
    q2 = parse_query("from S delete T on T.sym == sym")
    assert isinstance(q2.output, ast.DeleteFrom)
    q3 = parse_query("from S select * update T set T.p = p + 1 on T.sym == sym")
    assert isinstance(q3.output, ast.UpdateTable)


def test_aggregation_definition():
    app = parse("""
        define stream S (symbol string, price double, ts long);
        define aggregation TradeAgg
        from S
        select symbol, avg(price) as ap, sum(price) as total
        group by symbol
        aggregate by ts every sec ... year;
    """)
    agg = app.aggregation_definitions["TradeAgg"]
    assert agg.by_attribute == ast.Variable("ts")
    assert agg.durations[0] == ast.Duration.SECONDS
    assert agg.durations[-1] == ast.Duration.YEARS
    assert len(agg.durations) == 7


def test_trigger_definitions():
    app = parse("""
        define trigger T5 at every 5 sec;
        define trigger TStart at 'start';
        define trigger TCron at '*/5 * * * * ?';
    """)
    assert app.trigger_definitions["T5"].at_every_millis == 5000
    assert app.trigger_definitions["TStart"].at_start
    assert app.trigger_definitions["TCron"].at_cron == "*/5 * * * * ?"
    # triggers define an implicit stream
    assert "T5" in app.stream_definitions


def test_expressions():
    e = parse_expression("a + b * 2 - c / 4 % 3")
    # a + ((b*2)) - ((c/4)%3)  with left assoc
    assert isinstance(e, ast.Math) and e.op == MathOp.SUB
    e2 = parse_expression("not (a == 1 or b is null) and c in T")
    assert isinstance(e2, ast.And)
    assert isinstance(e2.left, ast.Not)
    assert isinstance(e2.right, ast.In)
    e3 = parse_expression("str:concat(a, 'x')")
    assert e3 == ast.FunctionCall("concat", (ast.Variable("a"),
                                             ast.Constant("x", AttrType.STRING)),
                                  namespace="str")
    e4 = parse_expression("-5")
    assert e4 == ast.Constant(-5, AttrType.INT)


def test_ifthenelse_and_functions():
    e = parse_expression("ifThenElse(p > 10, 'hi', 'lo')")
    assert isinstance(e, ast.FunctionCall)
    assert len(e.args) == 3


def test_source_sink_annotations():
    app = parse("""
        @source(type='inMemory', topic='t1', @map(type='passThrough'))
        define stream In (a int);
        @sink(type='inMemory', topic='t2', @map(type='json'))
        define stream Out (a int);
        from In select a insert into Out;
    """)
    src = ast.find_annotation(app.stream_definitions["In"].annotations, "source")
    assert src.element("type") == "inMemory"
    assert src.annotations[0].name == "map"


def test_define_window_and_named_window_use():
    app = parse("""
        define stream S (a int);
        define window W (a int) length(5) output all events;
        from S insert into W;
        from W select a insert into O;
    """)
    wd = app.window_definitions["W"]
    assert wd.window.name == "length"
    assert len(app.execution_elements) == 2


def test_function_definition():
    app = parse("""
        define function concatFn[javascript] return string {
            var x = { a: 1 };
            return data[0] + data[1];
        };
    """)
    fd = app.function_definitions["concatFn"]
    assert fd.language == "javascript"
    assert fd.return_type == AttrType.STRING
    assert "data[0] + data[1]" in fd.body


def test_absent_logical_pattern():
    q = parse_query("""
        from e1=RegulatorStream -> not TempStream[temp > e1.temp] and e2=HumidStream
        select e1.temp insert into O
    """)
    lg = q.input.state.next
    assert isinstance(lg, ast.LogicalStateElement)
    assert isinstance(lg.left, ast.AbsentStreamStateElement)
    assert lg.op == "and"


def test_parse_errors():
    with pytest.raises(Exception):
        parse("define stream S (a unknowntype);")
    with pytest.raises(Exception):
        parse_query("from S select a")   # missing output action
