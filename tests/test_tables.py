"""Tables: define/insert/update/delete/update-or-insert, PK + secondary
indexes, table joins, `in Table` membership, snapshots.

Mirrors the reference's table test surface (reference:
modules/siddhi-core/src/test/java/org/wso2/siddhi/core/query/table/ —
InsertIntoTableTestCase, UpdateFromTableTestCase, DeleteFromTableTestCase,
UpdateOrInsertTableTestCase, JoinTableTestCase, IndexedTableTestCase).
"""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.planner import PlanError


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def table_rows(rt, tid):
    return sorted(rt.tables[tid].all_rows())


# -- insert ------------------------------------------------------------------

def test_insert_into_table(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (symbol string, price double, volume long);
        define table T (symbol string, price double, volume long);
        from S select symbol, price, volume insert into T;
    """)
    h = rt.input_handler("S")
    h.send(("WSO2", 55.6, 100))
    h.send(("IBM", 75.6, 10))
    rt.flush()
    assert table_rows(rt, "T") == [("IBM", 75.6, 10), ("WSO2", 55.6, 100)]
    assert len(rt.tables["T"]) == 2


def test_insert_with_filter_and_projection(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (symbol string, price double);
        define table T (symbol string, price double);
        from S[price > 50] select symbol, price insert into T;
    """)
    h = rt.input_handler("S")
    h.send([("A", 10.0), ("B", 60.0), ("C", 70.0)])
    rt.flush()
    assert table_rows(rt, "T") == [("B", 60.0), ("C", 70.0)]


def test_insert_schema_mismatch_rejected(mgr):
    with pytest.raises(PlanError):
        mgr.create_app_runtime("""
            define stream S (a int, b string);
            define table T (x string, y int);
            from S select a, b insert into T;
        """)


def test_stream_from_table_rejected(mgr):
    with pytest.raises(PlanError):
        mgr.create_app_runtime("""
            define table T (a int);
            from T select a insert into O;
        """)


def test_duplicate_primary_key_dropped(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (k string, v int);
        @PrimaryKey('k')
        define table T (k string, v int);
        from S select k, v insert into T;
    """)
    h = rt.input_handler("S")
    h.send(("a", 1))
    with pytest.warns(RuntimeWarning):
        h.send(("a", 2))
        rt.flush()
    assert table_rows(rt, "T") == [("a", 1)]


# -- update / delete / update or insert --------------------------------------

APP_UPD = """
    define stream S (symbol string, price double);
    define stream U (symbol string, price double);
    define table T (symbol string, price double);
    from S select symbol, price insert into T;
    from U select symbol, price update T on T.symbol == symbol;
"""


def test_update_table(mgr):
    rt = mgr.create_app_runtime(APP_UPD)
    rt.input_handler("S").send([("A", 1.0), ("B", 2.0)])
    rt.flush()
    rt.input_handler("U").send(("A", 9.0))
    rt.flush()
    assert table_rows(rt, "T") == [("A", 9.0), ("B", 2.0)]


def test_update_with_set_clause(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (symbol string, price double);
        define stream U (symbol string, delta double);
        define table T (symbol string, price double);
        from S select symbol, price insert into T;
        from U select symbol, delta
            update T set T.price = T.price + delta on T.symbol == symbol;
    """)
    rt.input_handler("S").send([("A", 1.0), ("B", 2.0)])
    rt.flush()
    rt.input_handler("U").send(("B", 10.0))
    rt.flush()
    assert table_rows(rt, "T") == [("A", 1.0), ("B", 12.0)]


def test_delete_from_table(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (symbol string, price double);
        define stream D (symbol string);
        define table T (symbol string, price double);
        from S select symbol, price insert into T;
        from D select symbol delete T on T.symbol == symbol;
    """)
    rt.input_handler("S").send([("A", 1.0), ("B", 2.0), ("C", 3.0)])
    rt.flush()
    rt.input_handler("D").send(("B",))
    rt.flush()
    assert table_rows(rt, "T") == [("A", 1.0), ("C", 3.0)]


def test_update_or_insert(mgr):
    rt = mgr.create_app_runtime("""
        define stream U (symbol string, price double);
        define table T (symbol string, price double);
        from U select symbol, price
            update or insert into T on T.symbol == symbol;
    """)
    h = rt.input_handler("U")
    h.send(("A", 1.0))
    h.send(("B", 2.0))
    h.send(("A", 5.0))
    rt.flush()
    assert table_rows(rt, "T") == [("A", 5.0), ("B", 2.0)]


def test_update_on_unknown_table_rejected(mgr):
    with pytest.raises(PlanError):
        mgr.create_app_runtime("""
            define stream S (a int);
            from S select a update NoSuchTable on NoSuchTable.a == a;
        """)


# -- indexes -----------------------------------------------------------------

def test_primary_key_seek_used(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (k string, v int);
        define stream P (k string);
        @PrimaryKey('k')
        define table T (k string, v int);
        from S select k, v insert into T;
        from P join T on T.k == P.k select P.k as k, T.v as v insert into O;
    """)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    rt.input_handler("S").send([(f"k{i}", i) for i in range(100)])
    rt.flush()
    # the join's compiled condition must be a PK seek, not a scan
    plan = [p for p in rt._plans if getattr(p, "table_cond", None) is not None][0]
    assert plan.table_cond.pk_fns is not None
    rt.input_handler("P").send(("k42",))
    rt.flush()
    assert out == [("k42", 42)]


def test_secondary_index_seek(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (k string, grp string, v int);
        define stream P (grp string);
        @Index('grp')
        define table T (k string, grp string, v int);
        from S select k, grp, v insert into T;
        from P join T on T.grp == P.grp select T.k as k insert into O;
    """)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    rt.input_handler("S").send(
        [(f"k{i}", f"g{i % 3}", i) for i in range(9)])
    rt.flush()
    plan = [p for p in rt._plans if getattr(p, "table_cond", None) is not None][0]
    assert plan.table_cond.index_seeks
    rt.input_handler("P").send(("g1",))
    rt.flush()
    assert sorted(out) == [("k1",), ("k4",), ("k7",)]


def test_update_maintains_index(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (k string, v int);
        define stream U (k string, v int);
        define stream P (k string);
        @PrimaryKey('k')
        define table T (k string, v int);
        from S select k, v insert into T;
        from U select k, v update T on T.k == k;
        from P join T on T.k == P.k select T.v as v insert into O;
    """)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    rt.input_handler("S").send(("a", 1))
    rt.flush()
    rt.input_handler("U").send(("a", 99))
    rt.flush()
    rt.input_handler("P").send(("a",))
    rt.flush()
    assert out == [(99,)]


# -- table joins -------------------------------------------------------------

def test_table_join_basic(mgr):
    rt = mgr.create_app_runtime("""
        define stream CheckStream (symbol string, qty int);
        define stream StockStream (symbol string, price double);
        define table StockTable (symbol string, price double);
        from StockStream select symbol, price insert into StockTable;
        from CheckStream join StockTable on StockTable.symbol == CheckStream.symbol
            select CheckStream.symbol as symbol, StockTable.price as price,
                   CheckStream.qty as qty
            insert into OutStream;
    """)
    out = []
    rt.add_callback("OutStream", lambda evs: out.extend(e.data for e in evs))
    rt.input_handler("StockStream").send([("WSO2", 55.0), ("IBM", 75.0)])
    rt.flush()
    rt.input_handler("CheckStream").send(("WSO2", 10))
    rt.flush()
    assert out == [("WSO2", 55.0, 10)]


def test_table_join_residual_condition(mgr):
    rt = mgr.create_app_runtime("""
        define stream C (sym string, minp double);
        define table T (sym string, price double);
        define stream S (sym string, price double);
        from S select sym, price insert into T;
        from C join T on T.sym == C.sym and T.price > C.minp
            select T.sym as sym, T.price as price insert into O;
    """)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    rt.input_handler("S").send([("A", 10.0), ("A", 20.0)])
    rt.flush()
    rt.input_handler("C").send(("A", 15.0))
    rt.flush()
    assert out == [("A", 20.0)]


def test_table_left_outer_join_emits_nulls(mgr):
    rt = mgr.create_app_runtime("""
        define stream C (sym string);
        define table T (sym string, price double);
        from C left outer join T on T.sym == C.sym
            select C.sym as sym, T.price as price insert into O;
    """)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    rt.input_handler("C").send(("NOPE",))
    rt.flush()
    assert out == [("NOPE", None)]


def test_two_table_join_rejected(mgr):
    with pytest.raises(PlanError):
        mgr.create_app_runtime("""
            define table A (x int);
            define table B (x int);
            from A join B on A.x == B.x select A.x as x insert into O;
        """)


# -- `in Table` --------------------------------------------------------------

def test_in_table_filter(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (sym string, price double);
        define stream W (sym string);
        define table Watch (sym string);
        from W select sym insert into Watch;
        from S[(Watch.sym == S.sym) in Watch]
            select sym, price insert into O;
    """)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    rt.input_handler("W").send(("IBM",))
    rt.flush()
    rt.input_handler("S").send([("IBM", 75.0), ("WSO2", 55.0)])
    rt.flush()
    assert out == [("IBM", 75.0)]


# -- nulls & snapshot --------------------------------------------------------

def test_table_stores_nulls(mgr):
    rt = mgr.create_app_runtime("""
        define stream A (x int);
        define stream B (y int);
        define table T (x int, y int);
        from e1=A or e2=B select e1.x as x, e2.y as y insert into T;
    """)
    rt.input_handler("B").send((42,))
    rt.flush()
    assert table_rows(rt, "T") == [(None, 42)]


def test_table_snapshot_restore(mgr):
    app = """
        define stream S (k string, v int);
        @PrimaryKey('k')
        define table T (k string, v int);
        from S select k, v insert into T;
    """
    rt = mgr.create_app_runtime(app)
    rt.input_handler("S").send([("a", 1), ("b", 2)])
    rt.flush()
    snap = rt.snapshot()

    m2 = SiddhiManager()
    rt2 = m2.create_app_runtime(app)
    rt2.restore(snap)
    assert table_rows(rt2, "T") == [("a", 1), ("b", 2)]
    # indexes rebuilt: a PK duplicate is still rejected
    with pytest.warns(RuntimeWarning):
        rt2.input_handler("S").send(("a", 9))
        rt2.flush()
    assert table_rows(rt2, "T") == [("a", 1), ("b", 2)]
    m2.shutdown()


def test_delete_then_reinsert_pk(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (k string, v int);
        define stream D (k string);
        @PrimaryKey('k')
        define table T (k string, v int);
        from S select k, v insert into T;
        from D select k delete T on T.k == k;
    """)
    rt.input_handler("S").send(("a", 1))
    rt.flush()
    rt.input_handler("D").send(("a",))
    rt.flush()
    assert table_rows(rt, "T") == []
    rt.input_handler("S").send(("a", 2))
    rt.flush()
    assert table_rows(rt, "T") == [("a", 2)]


def test_compaction_preserves_contents(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (k int);
        define stream D (k int);
        define table T (k int);
        from S select k insert into T;
        from D select k delete T on T.k == k;
    """)
    rt.input_handler("S").send([(i,) for i in range(600)])
    rt.flush()
    rt.input_handler("D").send([(i,) for i in range(0, 600, 2)])
    rt.flush()
    assert len(rt.tables["T"]) == 300
    assert table_rows(rt, "T") == [(i,) for i in range(1, 600, 2)]
