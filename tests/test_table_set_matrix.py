"""Table set-update / update-or-insert / table-join scenario matrix,
ported (shapes, not code) from the reference suites:
.../query/table/set/SetUpdateInMemoryTableTestCase.java,
SetUpdateOrInsertInMemoryTableTestCase.java, and JoinTableTestCase.java
(VERDICT r4 #6 — joins/tables were the thinnest test axes)."""
import pytest

from siddhi_tpu import SiddhiManager

HEAD = ("define stream StockStream (symbol string, price double, "
        "volume long);\n"
        "define stream UpdateStockStream (symbol string, price double, "
        "volume long);\n"
        "define table StockTable (symbol string, price double, "
        "volume long);\n"
        "from StockStream insert into StockTable;\n")


def run(app, stocks, updates, extra_sends=()):
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    out = []
    if "outputStream" in app:
        rt.add_callback("outputStream",
                        lambda evs: out.extend(tuple(e.data) for e in evs))
    rt.start()
    for row in stocks:
        rt.send("StockStream", row)
    rt.flush()
    for row in updates:
        rt.send("UpdateStockStream", row)
    rt.flush()
    for sid, row in extra_sends:
        rt.send(sid, row)
    rt.flush()
    rows = sorted(rt.tables["StockTable"].all_rows())
    m.shutdown()
    return rows, out


STOCKS = [("WSO2", 55.6, 100), ("IBM", 75.6, 100)]


# -- SetUpdateInMemoryTableTestCase shapes --------------------------------

def test_set_update_all_columns():
    """setUpdate 1: set every column."""
    app = (HEAD + "from UpdateStockStream update StockTable "
           "set StockTable.price = price, StockTable.volume = volume "
           "on StockTable.symbol == symbol;\n")
    rows, _ = run(app, STOCKS, [("IBM", 100.0, 200)])
    assert rows == [("IBM", 100.0, 200), ("WSO2", 55.6, 100)]


def test_set_update_subset_of_columns():
    """setUpdate 2: set a subset; untouched columns keep values."""
    app = (HEAD + "from UpdateStockStream update StockTable "
           "set StockTable.price = price "
           "on StockTable.symbol == symbol;\n")
    rows, _ = run(app, STOCKS, [("IBM", 100.0, 999)])
    assert rows == [("IBM", 100.0, 100), ("WSO2", 55.6, 100)]


def test_set_update_constant_expression():
    """setUpdate 3: constant assignment."""
    app = (HEAD + "from UpdateStockStream update StockTable "
           "set StockTable.price = 0.0 "
           "on StockTable.symbol == symbol;\n")
    rows, _ = run(app, STOCKS, [("IBM", 1.0, 0)])
    assert ("IBM", 0.0, 100) in rows


def test_set_update_arithmetic_over_table_value():
    """setUpdate 4: assignment reading the table's own column."""
    app = (HEAD + "from UpdateStockStream update StockTable "
           "set StockTable.price = StockTable.price + 10.0 "
           "on StockTable.symbol == symbol;\n")
    rows, _ = run(app, STOCKS, [("IBM", 0.0, 0)])
    assert any(r[0] == "IBM" and abs(r[1] - 85.6) < 1e-9 for r in rows)


def test_set_update_condition_on_non_key():
    """setUpdate 5: condition over a non-key attribute hits many rows."""
    app = (HEAD + "from UpdateStockStream update StockTable "
           "set StockTable.volume = volume "
           "on StockTable.volume == 100;\n")
    rows, _ = run(app, STOCKS, [("ANY", 0.0, 7)])
    assert all(r[2] == 7 for r in rows)


def test_set_update_no_match_is_noop():
    app = (HEAD + "from UpdateStockStream update StockTable "
           "set StockTable.price = price "
           "on StockTable.symbol == symbol;\n")
    rows, _ = run(app, STOCKS, [("GOOG", 1.0, 1)])
    assert rows == [("IBM", 75.6, 100), ("WSO2", 55.6, 100)]


def test_set_update_event_expression():
    """setUpdate 7: assignment mixing event and table values."""
    app = (HEAD + "from UpdateStockStream update StockTable "
           "set StockTable.volume = StockTable.volume + volume "
           "on StockTable.symbol == symbol;\n")
    rows, _ = run(app, STOCKS, [("WSO2", 0.0, 11)])
    assert ("WSO2", 55.6, 111) in rows


# -- SetUpdateOrInsertInMemoryTableTestCase shapes ------------------------

def test_set_update_or_insert_updates_existing():
    app = (HEAD + "from UpdateStockStream update or insert into StockTable "
           "set StockTable.price = price "
           "on StockTable.symbol == symbol;\n")
    rows, _ = run(app, STOCKS, [("IBM", 200.0, 0)])
    assert ("IBM", 200.0, 100) in rows and len(rows) == 2


def test_set_update_or_insert_inserts_missing():
    app = (HEAD + "from UpdateStockStream update or insert into StockTable "
           "set StockTable.price = price "
           "on StockTable.symbol == symbol;\n")
    rows, _ = run(app, STOCKS, [("GOOG", 10.0, 5)])
    assert ("GOOG", 10.0, 5) in rows and len(rows) == 3


def test_set_update_or_insert_subset_insert_carries_event_row():
    """The inserted row is the arriving event, not just the set columns."""
    app = (HEAD + "from UpdateStockStream update or insert into StockTable "
           "set StockTable.volume = 1 "
           "on StockTable.symbol == symbol;\n")
    rows, _ = run(app, STOCKS, [("MSFT", 9.0, 500)])
    assert ("MSFT", 9.0, 500) in rows           # inserted as sent
    rows2, _ = run(app, STOCKS, [("WSO2", 0.0, 500)])
    assert ("WSO2", 55.6, 1) in rows2           # updated via set only


# -- JoinTableTestCase shapes ---------------------------------------------

def test_table_join_basic():
    app = (HEAD +
           "define stream CheckStream (symbol string);\n"
           "@info(name='q') from CheckStream join StockTable "
           "on CheckStream.symbol == StockTable.symbol "
           "select CheckStream.symbol as symbol, StockTable.price as price "
           "insert into outputStream;\n")
    _rows, out = run(app, STOCKS, [],
                     extra_sends=[("CheckStream", ("WSO2",))])
    assert out == [("WSO2", 55.6)]


def test_table_join_no_match_inner_silent():
    app = (HEAD +
           "define stream CheckStream (symbol string);\n"
           "@info(name='q') from CheckStream join StockTable "
           "on CheckStream.symbol == StockTable.symbol "
           "select CheckStream.symbol as symbol, StockTable.price as price "
           "insert into outputStream;\n")
    _rows, out = run(app, STOCKS, [],
                     extra_sends=[("CheckStream", ("GOOG",))])
    assert out == []


def test_table_join_left_outer_emits_nulls():
    """JoinTableTestCase left-outer shape: stream side emits with nulls."""
    app = (HEAD +
           "define stream CheckStream (symbol string);\n"
           "@info(name='q') from CheckStream left outer join StockTable "
           "on CheckStream.symbol == StockTable.symbol "
           "select CheckStream.symbol as symbol, StockTable.price as price "
           "insert into outputStream;\n")
    _rows, out = run(app, STOCKS, [],
                     extra_sends=[("CheckStream", ("GOOG",))])
    assert out == [("GOOG", None)]


def test_table_join_count_aggregation():
    """joinTest (dataTable count): count() over table join."""
    app = (HEAD +
           "define stream CountIn (symbol string);\n"
           "@info(name='q') from CountIn join StockTable "
           "on StockTable.volume == 100 "
           "select count() as c insert into outputStream;\n")
    _rows, out = run(app, STOCKS, [],
                     extra_sends=[("CountIn", ("x",))])
    # running count per joined row; the final value covers both rows
    # (the reference asserts the last received event's count == 2)
    assert out[-1] == (2,)


def test_table_join_condition_on_table_attr():
    app = (HEAD +
           "define stream CheckStream (limitp double);\n"
           "@info(name='q') from CheckStream join StockTable "
           "on StockTable.price > CheckStream.limitp "
           "select StockTable.symbol as symbol insert into outputStream;\n")
    _rows, out = run(app, STOCKS, [],
                     extra_sends=[("CheckStream", (60.0,))])
    assert out == [("IBM",)]


def test_table_join_unidirectional_implicit():
    """A table never triggers: only stream arrivals emit."""
    app = (HEAD +
           "define stream CheckStream (symbol string);\n"
           "@info(name='q') from StockTable join CheckStream "
           "on CheckStream.symbol == StockTable.symbol "
           "select StockTable.price as price insert into outputStream;\n")
    _rows, out = run(app, STOCKS, [],
                     extra_sends=[("CheckStream", ("IBM",))])
    assert out == [(75.6,)]


# -- delete + update interplay (UpdateFromTableTestCase flavor) -----------

def test_update_then_delete_sequence():
    app = (HEAD +
           "define stream DeleteStream (symbol string);\n"
           "from UpdateStockStream update StockTable "
           "set StockTable.price = price on StockTable.symbol == symbol;\n"
           "from DeleteStream delete StockTable "
           "on StockTable.symbol == symbol;\n")
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    rt.start()
    for row in STOCKS:
        rt.send("StockStream", row)
    rt.send("UpdateStockStream", ("IBM", 1.0, 0))
    rt.send("DeleteStream", ("WSO2",))
    rt.flush()
    rows = sorted(rt.tables["StockTable"].all_rows())
    m.shutdown()
    assert rows == [("IBM", 1.0, 100)]
