"""Device window-aggregation plans: differential equality against the
sequential host interpreter on randomized streams (the device kernel's
claim is exact reference semantics — SURVEY §4 differential strategy)."""
import random

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.window_device import DeviceWindowAggPlan


def run_app(app, rows, batch_sizes=None, rng=None):
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    out = []
    rt.add_callback("O", lambda evs: out.extend((e.timestamp, e.data)
                                                for e in evs))
    h = rt.input_handler("S")
    i = 0
    while i < len(rows):
        n = (batch_sizes and batch_sizes.pop(0)) or \
            (rng.randint(1, 7) if rng else 1)
        for ts, row in rows[i:i + n]:
            h.send(row, timestamp=ts)
        rt.flush()
        i += n
    rt.flush()
    m.shutdown()
    return out


def differential(query, rows, seed=0):
    head = "@app:playback define stream S (sym string, p double, v long);\n"
    dev_app = "@app:deviceWindows('always')\n" + head + query
    host_app = "@app:deviceWindows('never')\n" + head + query
    rng1, rng2 = random.Random(seed), random.Random(seed)
    dev = run_app(dev_app, rows, rng=rng1)
    host = run_app(host_app, rows, rng=rng2)
    assert len(dev) == len(host), (len(dev), len(host))
    for d, h in zip(dev, host):
        assert d[0] == h[0], (d, h)
        for a, b in zip(d[1], h[1]):
            if isinstance(a, float):
                assert b == pytest.approx(a, rel=2e-5, abs=2e-4), (d, h)
            else:
                assert a == b, (d, h)


def gen_rows(n, n_syms=3, seed=1):
    r = random.Random(seed)
    ts = 1000
    rows = []
    for _ in range(n):
        ts += r.randint(0, 400)
        rows.append((ts, (f"s{r.randint(0, n_syms - 1)}",
                          round(r.uniform(-50, 150), 2), r.randint(1, 9))))
    return rows


QUERIES = [
    "from S#window.length(5) select sym, sum(p) as s, count() as c "
    "insert into O;",
    "from S#window.length(1) select sum(p) as s insert into O;",
    "from S#window.length(7) select sym, sum(p) as s group by sym "
    "insert into O;",
    "from S#window.length(4) select min(p) as lo, max(p) as hi, avg(p) as m "
    "insert into O;",
    "from S#window.time(1 sec) select sum(p) as s, count() as c "
    "insert into O;",
    "from S#window.time(700 milliseconds) select sym, avg(p) as m "
    "group by sym insert into O;",
    "from S#window.lengthBatch(4) select sym, sum(p) as s group by sym "
    "insert into O;",
    "from S#window.lengthBatch(3) select min(p) as lo, max(p) as hi "
    "insert into O;",
    "from S[p > 0]#window.length(5) select sym, sum(p) as s insert into O;",
    "from S#window.length(6) select sym, sum(p) as s group by sym "
    "having s > 100.0 insert into O;",
    "from S#window.time(2 sec) select sum(v) as sv, avg(p) as ap "
    "group by sym insert into O;",
]


@pytest.mark.parametrize("qi", [
    pytest.param(i, marks=pytest.mark.slow) if i == 2 else i
    for i in range(len(QUERIES))])
def test_differential(qi):
    differential(QUERIES[qi], gen_rows(120, seed=qi + 10), seed=qi)


def test_differential_large_batches():
    # batch boundaries crossing window size + carry growth
    rows = gen_rows(400, n_syms=5, seed=99)
    differential("from S#window.time(300 milliseconds) select sym, "
                 "sum(p) as s group by sym insert into O;", rows, seed=7)


def test_device_snapshot_restore():
    app = ("@app:deviceWindows('always') @app:playback\n"
           "define stream S (sym string, p double, v long);\n"
           "from S#window.length(4) select sum(p) as s insert into O;")
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    h = rt.input_handler("S")
    for i, (ts, row) in enumerate(gen_rows(10, seed=3)):
        h.send(row, timestamp=ts)
    rt.flush()
    snap = rt.snapshot()

    m2 = SiddhiManager()
    rt2 = m2.create_app_runtime(app)
    out2 = []
    rt2.add_callback("O", lambda evs: out2.extend(e.data for e in evs))
    rt2.restore(snap)
    extra = gen_rows(6, seed=4)
    for ts, row in extra:
        rt2.input_handler("S").send(row, timestamp=ts)
    rt2.flush()
    # continuity: same as uninterrupted run
    m3 = SiddhiManager()
    rt3 = m3.create_app_runtime(app)
    out3 = []
    rt3.add_callback("O", lambda evs: out3.extend(e.data for e in evs))
    for ts, row in gen_rows(10, seed=3) + extra:
        rt3.input_handler("S").send(row, timestamp=ts)
    rt3.flush()
    a = [v for row in out + out2 for v in row]
    b = [v for row in out3 for v in row]
    assert a == pytest.approx(b, rel=2e-5, abs=2e-4)
    m.shutdown(); m2.shutdown(); m3.shutdown()


def test_carry_overflow_grows():
    # tiny initial carry forces growth for a long time window
    app = ("@app:deviceWindows('always') @app:playback\n"
           "define stream S (sym string, p double, v long);\n"
           "from S#window.time(1 hour) select count() as c insert into O;")
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    plan = rt._plans[0]
    assert isinstance(plan, DeviceWindowAggPlan)
    plan.C = 8
    plan.state = plan._init_state()
    out = []
    rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
    h = rt.input_handler("S")
    ts = 1000
    for i in range(50):
        ts += 10
        h.send(("x", 1.0, 1), timestamp=ts)
    rt.flush()
    assert plan.C > 8
    assert out[-1] == (50,)
    m.shutdown()


def test_f64_all_double_outputs():
    """Slim pack with every output column DOUBLE in f64 mode: the i-pack
    is empty and must be omitted, not stacked (r4 review finding)."""
    rows = gen_rows(60, seed=42)
    head = ("@app:devicePrecision('f64')\n@app:playback "
            "define stream S (sym string, p double, v long);\n")
    q = "from S#window.length(5) select avg(p) as m, sum(p) as s insert into O;"
    import random as _r
    dev = run_app("@app:deviceWindows('always')\n" + head + q, rows,
                  rng=_r.Random(1))
    host = run_app("@app:deviceWindows('never')\n" + head + q, rows,
                   rng=_r.Random(1))
    assert len(dev) == len(host)
    for d, h in zip(dev, host):
        assert d[0] == h[0]
        for a, b in zip(d[1], h[1]):
            assert b == pytest.approx(a, rel=1e-9)


# -- r5 widening: grouped sliding min/max, externalTime, order-by/limit ---

@pytest.mark.parametrize("q", [
    "from S#window.length(9) select sym, min(p) as lo, max(p) as hi "
    "group by sym insert into O;",
    "from S#window.length(4) select sym, max(p) as hi, sum(v) as sv "
    "group by sym having hi > 50.0 insert into O;",
    "from S#window.time(800) select sym, min(p) as lo group by sym "
    "insert into O;",
])
def test_grouped_sliding_minmax(q):
    differential(q, gen_rows(160, seed=31), seed=31)


def test_grouped_sliding_minmax_device_engaged():
    m = SiddhiManager()
    rt = m.create_app_runtime(
        "@app:deviceWindows('always')\n"
        "define stream S (sym string, p double, v long);\n"
        "from S#window.length(5) select sym, min(p) as lo group by sym "
        "insert into O;")
    assert any(isinstance(p, DeviceWindowAggPlan) for p in rt._plans)
    m.shutdown()


def test_external_time_differential():
    """externalTime(et, D): window clock from an event attribute."""
    head = ("@app:playback define stream S (sym string, p double, "
            "v long, et long);\n")
    q = ("from S#window.externalTime(et, 700) select sym, avg(p) as ap, "
         "count() as c group by sym insert into O;")
    r = random.Random(41)
    ts, et = 1000, 50_000
    rows = []
    for _ in range(150):
        ts += r.randint(1, 50)
        et += r.randint(0, 300)
        rows.append((ts, (f"s{r.randint(0, 2)}",
                          round(r.uniform(0, 90), 2), r.randint(1, 9), et)))
    dev_app = "@app:deviceWindows('always')\n" + head + q
    host_app = "@app:deviceWindows('never')\n" + head + q
    dev = run_app(dev_app, rows, rng=random.Random(5))
    host = run_app(host_app, rows, rng=random.Random(5))
    assert len(dev) == len(host), (len(dev), len(host))
    for d, h in zip(dev, host):
        assert d[0] == h[0], (d, h)
        for a, b in zip(d[1], h[1]):
            if isinstance(a, float):
                assert b == pytest.approx(a, rel=2e-5, abs=2e-4), (d, h)
            else:
                assert a == b, (d, h)


def test_external_time_device_engaged():
    m = SiddhiManager()
    rt = m.create_app_runtime(
        "@app:deviceWindows('always')\n"
        "define stream S (sym string, p double, et long);\n"
        "from S#window.externalTime(et, 500) select sum(p) as s "
        "insert into O;")
    assert any(isinstance(p, DeviceWindowAggPlan) for p in rt._plans)
    m.shutdown()


@pytest.mark.parametrize("q", [
    "from S#window.length(6) select sym, sum(p) as s group by sym "
    "order by s insert into O;",
    "from S#window.length(6) select sym, sum(p) as s group by sym "
    "order by s desc limit 2 insert into O;",
    "from S#window.lengthBatch(8) select sym, count() as c group by sym "
    "order by sym limit 2 offset 1 insert into O;",
])
def test_order_by_limit_on_device_outputs(q):
    differential(q, gen_rows(120, seed=51), seed=51)


def test_order_by_device_engaged():
    m = SiddhiManager()
    rt = m.create_app_runtime(
        "@app:deviceWindows('always')\n"
        "define stream S (sym string, p double, v long);\n"
        "from S#window.length(5) select sym, sum(p) as s group by sym "
        "order by s desc limit 3 insert into O;")
    assert any(isinstance(p, DeviceWindowAggPlan) for p in rt._plans)
    m.shutdown()


def _differential_et(q, rows, seed):
    head = ("@app:playback define stream S (sym string, p double, "
            "v long, et long);\n")
    dev = run_app("@app:deviceWindows('always')\n" + head + q, rows,
                  rng=random.Random(seed))
    host = run_app("@app:deviceWindows('never')\n" + head + q, rows,
                   rng=random.Random(seed))
    assert len(dev) == len(host), (len(dev), len(host), dev[:3], host[:3])
    for d, h in zip(dev, host):
        assert d[0] == h[0], (d, h)
        for a, b in zip(d[1], h[1]):
            if isinstance(a, float):
                assert b == pytest.approx(a, rel=2e-5, abs=2e-4), (d, h)
            else:
                assert a == b, (d, h)


def _et_rows(n, seed, gap=300):
    r = random.Random(seed)
    ts, et = 1000, 50_000
    rows = []
    for _ in range(n):
        ts += r.randint(1, 50)
        et += r.randint(0, gap)
        rows.append((ts, (f"s{r.randint(0, 2)}",
                          round(r.uniform(0, 90), 2), r.randint(1, 9), et)))
    return rows


@pytest.mark.parametrize("q", [
    "from S#window.externalTimeBatch(et, 700) select sum(p) as s, "
    "count() as c insert into O;",
    "from S#window.externalTimeBatch(et, 900) select sym, max(p) as hi, "
    "avg(v) as av group by sym insert into O;",
])
def test_external_time_batch_differential(q):
    _differential_et(q, _et_rows(150, 61), 61)


def test_external_time_batch_sparse_buckets():
    """Empty buckets between events emit nothing (the reference advances
    start through them silently)."""
    _differential_et(
        "from S#window.externalTimeBatch(et, 200) select count() as c "
        "insert into O;", _et_rows(80, 62, gap=1500), 62)


def test_external_time_batch_filtered_first_batch_anchor():
    """A fully-filtered first micro-batch must NOT latch the bucket
    anchor: the device kernel's argmax over an all-False valid mask
    points at carry slot 0, and latching that garbage event-time would
    permanently shift every bucket boundary vs the host path."""
    q = ("from S[p > 0]#window.externalTimeBatch(et, 700) "
         "select sum(p) as s, count() as c insert into O;")
    r = random.Random(7)
    ts, et, rows = 1000, 50_000, []
    for i in range(60):
        ts += r.randint(1, 50)
        et += r.randint(0, 300)
        # the first 6 rows (batch 1, see batch_sizes below) all fail the
        # filter; later rows mix pass/fail
        p = round(r.uniform(-90.0, -1.0), 2) if i < 6 \
            else round(r.uniform(-50.0, 90.0), 2)
        rows.append((ts, ("s0", p, 1, et)))
    head = ("@app:playback define stream S (sym string, p double, "
            "v long, et long);\n")
    dev = run_app("@app:deviceWindows('always')\n" + head + q, rows,
                  batch_sizes=[6] + [5] * 100)
    host = run_app("@app:deviceWindows('never')\n" + head + q, rows,
                   batch_sizes=[6] + [5] * 100)
    assert len(dev) == len(host) and dev, (len(dev), len(host))
    for d, h in zip(dev, host):
        assert d[0] == h[0], (d, h)
        for a, b in zip(d[1], h[1]):
            if isinstance(a, float):
                assert b == pytest.approx(a, rel=2e-5, abs=2e-4), (d, h)
            else:
                assert a == b, (d, h)


def test_external_time_batch_device_engaged():
    m = SiddhiManager()
    rt = m.create_app_runtime(
        "@app:deviceWindows('always')\n"
        "define stream S (sym string, p double, et long);\n"
        "from S#window.externalTimeBatch(et, 500) select sum(p) as s "
        "insert into O;")
    assert any(isinstance(p, DeviceWindowAggPlan) for p in rt._plans)
    m.shutdown()
