"""Durability subsystem (core/wal.py + runtime recovery wiring):
admitted-frame WAL, snapshot-coordinated exactly-once crash recovery,
the torn-write/corrupt-segment matrix (mirroring the test_persistence
corruption philosophy), segment truncation behind snapshot barriers,
durable-ACK over the frame plane, and the structured revision
descriptor + service snapshot endpoint."""
import glob
import os
import warnings

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.persistence import (FileSystemPersistenceStore,
                                         Revision)
from siddhi_tpu.core.wal import WriteAheadLog

APP = """
@app:name('Dur')
@app:durability('batch')
define stream S (sym string, p double);
define table T (sym string, p double);
@info(name='ins') from S select sym, p insert into T;
"""

PATTERN = """
@app:name('DurPat')
@app:durability('batch')
define stream S (sym string, p double);
define table M (s1 string, p2 double);
@info(name='q') from every e1=S[p > 100] -> e2=S[p > e1.p] within 1 sec
select e1.sym as s1, e2.p as p2 insert into M;
"""


def frames(n_frames=6, batch=32, seed=3):
    rng = np.random.default_rng(seed)
    ts0 = 1_700_000_000_000
    return [({"sym": np.array([f"K{i}" for i in
                               rng.integers(0, 4, batch)]),
              "p": np.round(rng.uniform(90, 130, batch), 2)},
             ts0 + np.arange(k * batch, (k + 1) * batch,
                             dtype=np.int64))
            for k in range(n_frames)]


def feed(rt, frs, stream="S"):
    h = rt.input_handler(stream)
    for cols, ts in frs:
        h.send_batch(cols, ts)
    rt.flush()


def table_rows(rt, name):
    return sorted(map(tuple, rt.tables[name].all_rows()))


def crash(mgr, rt):
    """Simulate SIGKILL: release the log file without the graceful
    shutdown barrier/close path (no flush-of-builders, no final sync
    beyond what the policy already did)."""
    if rt.wal is not None:
        rt.wal.close()
    mgr._runtimes.clear()


def fresh(tmp_path, app=APP):
    mgr = SiddhiManager()
    mgr.set_persistence_store(FileSystemPersistenceStore(str(tmp_path)))
    rt = mgr.create_app_runtime(app)
    return mgr, rt


# ---------------------------------------------------------------------------
# exactly-once recovery roundtrips
# ---------------------------------------------------------------------------

def test_recover_without_snapshot_replays_everything(tmp_path):
    frs = frames()
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frs)
    want = table_rows(rt, "T")
    crash(mgr, rt)

    m2, rt2 = fresh(tmp_path)
    rep = rt2.recover()
    assert rep["restored_revision"] is None
    assert rep["replayed_frames"] == len(frs)
    assert table_rows(rt2, "T") == want
    m2.shutdown()


def test_recover_skips_at_or_below_watermark(tmp_path):
    """Snapshot mid-stream: recovery must restore + replay ONLY the
    suffix — zero duplicate rows, zero lost rows."""
    frs = frames(8)
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frs[:5])
    rev = rt.persist()
    assert rev.watermark == {"S": 5}
    feed(rt, frs[5:])
    want = table_rows(rt, "T")
    crash(mgr, rt)

    m2, rt2 = fresh(tmp_path)
    rep = rt2.recover()
    assert rep["restored_revision"] == str(rev)
    assert rep["watermark"] == {"S": 5}
    # the synchronous persist truncated the pre-watermark segments, so
    # nothing even needed skipping; everything replayed is the suffix
    assert rep["replayed_frames"] == 3
    assert table_rows(rt2, "T") == want
    m2.shutdown()


def test_recover_stateful_pattern_exactly_once(tmp_path):
    """Pattern state (pending instances) rides the snapshot; the WAL
    suffix re-arms and completes them — matches byte-identical to an
    uninterrupted run."""
    frs = frames(8, seed=11)
    mgr, rt = fresh(tmp_path, PATTERN)
    rt.start()
    feed(rt, frs)
    want = table_rows(rt, "M")
    assert want                          # the tape produces matches
    crash(mgr, rt)

    m2, rt2 = fresh(tmp_path, PATTERN)
    rt2.recover()
    assert table_rows(rt2, "M") == want
    m2.shutdown()

    # and with a mid-stream snapshot barrier
    m3, rt3 = fresh(tmp_path / "b", PATTERN)
    rt3.start()
    feed(rt3, frs[:4])
    rt3.persist()
    feed(rt3, frs[4:])
    assert table_rows(rt3, "M") == want
    crash(m3, rt3)
    m4, rt4 = fresh(tmp_path / "b", PATTERN)
    rep = rt4.recover()
    assert rep["replayed_frames"] == 4 and rep["watermark"] == {"S": 4}
    assert table_rows(rt4, "M") == want
    m4.shutdown()


def test_double_recovery_is_idempotent(tmp_path):
    """Recover, crash again WITHOUT new ingest, recover again: the
    second recovery must not double-apply (fresh snapshotless runs
    replay the same prefix into fresh state — same rows, not more)."""
    frs = frames(4)
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frs)
    want = table_rows(rt, "T")
    crash(mgr, rt)
    m2, rt2 = fresh(tmp_path)
    rt2.recover()
    assert table_rows(rt2, "T") == want
    crash(m2, rt2)
    m3, rt3 = fresh(tmp_path)
    rt3.recover()
    assert table_rows(rt3, "T") == want
    m3.shutdown()


# ---------------------------------------------------------------------------
# corruption matrix (mirrors test_persistence's corrupt-skip philosophy)
# ---------------------------------------------------------------------------

def _wal_dir(tmp_path, app="Dur"):
    return os.path.join(str(tmp_path), app, "wal")


def _segs(tmp_path, app="Dur"):
    return sorted(glob.glob(os.path.join(_wal_dir(tmp_path, app),
                                         "wal-*.seg")))


def test_torn_tail_truncate_mid_record(tmp_path):
    """Truncate the newest segment mid-record (a crash mid-append):
    recovery applies the longest valid prefix and heals the file."""
    frs = frames(5)
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frs)
    crash(mgr, rt)
    seg = _segs(tmp_path)[-1]
    os.truncate(seg, os.path.getsize(seg) - 9)

    m2, rt2 = fresh(tmp_path)
    rep = rt2.recover()
    assert rep["replayed_frames"] == 4
    assert rep["corrupt_skipped"] >= 1
    assert rt2.wal.metrics()["corrupt_skipped"] >= 1
    assert rt2.statistics()["durability"]["corrupt_skipped"] >= 1
    # post-heal ingest + a THIRD recovery sees old prefix + new frames
    feed(rt2, frames(1, seed=99))
    crash(m2, rt2)
    m3, rt3 = fresh(tmp_path)
    rep3 = rt3.recover()
    assert rep3["replayed_frames"] == 5 and rep3["corrupt_skipped"] == 0
    m3.shutdown()


def test_bitflip_in_sealed_segment_stops_at_scar(tmp_path):
    """Flip bytes inside a SEALED (older) segment: replay must stop at
    the last valid record BEFORE the flip — frames after it (whose
    pre-state is now unprovable) are dropped and counted, never
    half-applied."""
    frs = frames(6)
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frs[:4])
    rt.wal.rotate()                     # seal segment 1 (frames 1-4)
    feed(rt, frs[4:])                   # segment 2 (frames 5-6)
    crash(mgr, rt)
    sealed = _segs(tmp_path)[0]
    blob = bytearray(open(sealed, "rb").read())
    blob[len(blob) // 2] ^= 0xFF        # scar mid-segment
    open(sealed, "wb").write(bytes(blob))

    m2, rt2 = fresh(tmp_path)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rep = rt2.recover()
    assert 0 < rep["replayed_frames"] < 4
    assert rep["corrupt_skipped"] >= 1
    # the unreachable newer segment was quarantined, not deleted
    q = glob.glob(os.path.join(_wal_dir(tmp_path), "*.quarantined"))
    assert q
    m2.shutdown()


def test_deleted_newest_segment_recovers_prefix(tmp_path):
    frs = frames(6)
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frs[:3])
    rt.wal.rotate()
    feed(rt, frs[3:])
    crash(mgr, rt)
    os.remove(_segs(tmp_path)[-1])

    m2, rt2 = fresh(tmp_path)
    rep = rt2.recover()
    assert rep["replayed_frames"] == 3
    # seqs resume past the lost frames' watermark: new ingest appends
    # at seq 4, and the next recovery replays prefix + new frame
    feed(rt2, frames(1, seed=5))
    assert rt2.wal.seqs["S"] == 4
    crash(m2, rt2)
    m3, rt3 = fresh(tmp_path)
    assert rt3.recover()["replayed_frames"] == 4
    m3.shutdown()


def test_missing_middle_segment_stops_before_gap(tmp_path):
    frs = frames(9)
    mgr, rt = fresh(tmp_path)
    rt.start()
    for i in range(3):
        feed(rt, frs[i * 3:(i + 1) * 3])
        if i < 2:
            rt.wal.rotate()
    crash(mgr, rt)
    segs = _segs(tmp_path)
    assert len(segs) == 3
    os.remove(segs[1])                  # the gap

    m2, rt2 = fresh(tmp_path)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rep = rt2.recover()
    assert rep["replayed_frames"] == 3  # segment 1 only
    assert rep["corrupt_skipped"] >= 1
    m2.shutdown()


# ---------------------------------------------------------------------------
# segments, truncation, barriers
# ---------------------------------------------------------------------------

def test_segment_rotation_and_snapshot_truncation(tmp_path):
    app = APP.replace("@app:durability('batch')",
                      "@app:durability('batch', segment.bytes='256')")
    mgr, rt = fresh(tmp_path, app)
    rt.start()
    feed(rt, frames(6))
    assert len(_segs(tmp_path)) > 1     # tiny segments rotated
    n_before = len(_segs(tmp_path))
    rev = rt.persist()                  # barrier: rotate + truncate
    assert rt.wal.truncated_segments >= n_before - 1
    # every surviving frame is covered by the snapshot watermark
    left = _segs(tmp_path)
    assert len(left) <= 2               # the fresh open segment (+seal)
    # post-snapshot ingest lands in the new segment and replays alone
    feed(rt, frames(2, seed=42))
    want = table_rows(rt, "T")
    crash(mgr, rt)
    m2, rt2 = fresh(tmp_path, app)
    rep = rt2.recover()
    assert rep["watermark"] == dict(rev.watermark)
    assert rep["replayed_frames"] == 2
    assert table_rows(rt2, "T") == want
    m2.shutdown()


def test_async_persist_does_not_truncate(tmp_path):
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frames(3))
    rt.wal.rotate()
    rev = rt.persist(asynchronous=True)
    rt.persistor().wait()
    assert rt.persistor().errors == []
    assert rt.wal.truncated_segments == 0   # async: suffix must survive
    assert rev.watermark == {"S": 3}
    mgr.shutdown()


# ---------------------------------------------------------------------------
# sync policies + durable ACK over the frame plane
# ---------------------------------------------------------------------------

def test_fsync_policy_syncs_per_append(tmp_path):
    app = APP.replace("'batch'", "'fsync'")
    mgr, rt = fresh(tmp_path, app)
    rt.start()
    feed(rt, frames(3))
    m = rt.wal.metrics()
    assert m["policy"] == "fsync" and m["fsyncs"] >= 3
    assert m["fsync"]["batches"] == m["fsyncs"]
    mgr.shutdown()


def test_tcp_ack_means_durable(tmp_path):
    """Frames ACK'd over the wire (client barrier) must be in the log,
    fsynced, BEFORE the ACK — a crash right after the barrier loses
    nothing the producer was told is safe."""
    from siddhi_tpu.net import TcpFrameClient
    app = ("@source(type='tcp', port='0')\n"
           + APP.replace("@app:name('Dur')", "@app:name('DurNet')"))
    mgr = SiddhiManager()
    mgr.set_persistence_store(FileSystemPersistenceStore(str(tmp_path)))
    rt = mgr.create_app_runtime(app)
    rt.start()
    cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "S",
                         TcpFrameClient.cols_of_schema(rt.schemas["S"]))
    frs = frames(4)
    for cols, ts in frs:
        cli.send_batch(cols, ts)
    cli.barrier(timeout=30)             # PING/ACK: the durability barrier
    want = table_rows(rt, "T")
    m = rt.wal.metrics()
    assert m["appended_frames"] == 4
    assert m["fsyncs"] >= 1             # the ACK barrier synced 'batch'
    cli.close()
    crash(mgr, rt)

    m2 = SiddhiManager()
    m2.set_persistence_store(FileSystemPersistenceStore(str(tmp_path)))
    rt2 = m2.create_app_runtime(app)
    rep = rt2.recover()
    assert rep["replayed_frames"] == 4
    assert table_rows(rt2, "T") == want
    m2.shutdown()


# ---------------------------------------------------------------------------
# descriptors, endpoints, disabled-loudly
# ---------------------------------------------------------------------------

def test_revision_descriptor_is_str_compatible(tmp_path):
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frames(2))
    rev = rt.persist()
    assert isinstance(rev, Revision) and isinstance(rev, str)
    store = mgr.persistence_store
    assert store.last_revision(rt.app.name) == rev      # str compare
    d = rev.to_dict()
    assert d["revision"] == str(rev)
    assert d["watermark"] == {"S": 2}
    assert d["durability"] == "batch" and d["incremental"] is False
    assert rt.last_revision_descriptor is rev
    # durability off -> watermark None, still a Revision
    m2 = SiddhiManager()
    m2.set_persistence_store(FileSystemPersistenceStore(str(tmp_path)))
    rt2 = m2.create_app_runtime(APP.replace("@app:durability('batch')\n",
                                            ""))
    rev2 = rt2.persist()
    assert isinstance(rev2, Revision) and rev2.watermark is None
    mgr.shutdown()
    m2.shutdown()


def test_service_snapshot_endpoint(tmp_path):
    import json
    import urllib.request
    from siddhi_tpu.service import SiddhiService
    mgr = SiddhiManager()
    mgr.set_persistence_store(FileSystemPersistenceStore(str(tmp_path)))
    svc = SiddhiService(port=0, manager=mgr, net=False).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        req = urllib.request.Request(f"{base}/siddhi/artifact/deploy",
                                     data=APP.encode(), method="POST")
        urllib.request.urlopen(req).read()
        svc.send_events({"app": "Dur", "stream": "S",
                         "data": ["A", 1.0]})
        req = urllib.request.Request(
            f"{base}/siddhi/artifact/snapshot",
            data=json.dumps({"app": "Dur"}).encode(), method="POST")
        out = json.loads(urllib.request.urlopen(req).read())
        assert out["watermark"] == {"S": 1}
        assert out["durability"] == "batch" and out["revision"]
        info = json.loads(urllib.request.urlopen(
            f"{base}/siddhi/artifact/snapshot?siddhiApp=Dur").read())
        assert info["last_revision"]["revision"] == out["revision"]
        assert info["wal"]["appended_frames"] == 1
        assert info["store_revision"] == out["revision"]
    finally:
        svc.stop()


def test_service_redeploy_recovers(tmp_path):
    """Same-name redeploy on a durable app resumes from the log instead
    of parking-only: match counts identical to the uninterrupted run."""
    from siddhi_tpu.service import SiddhiService
    frs = frames(6, seed=11)
    # uninterrupted reference
    mgr, rt = fresh(tmp_path / "ref", PATTERN)
    rt.start()
    feed(rt, frs)
    want = table_rows(rt, "M")
    mgr.shutdown()

    m2 = SiddhiManager()
    m2.set_persistence_store(
        FileSystemPersistenceStore(str(tmp_path / "svc")))
    svc = SiddhiService(port=0, manager=m2, net=False).start()
    try:
        svc.deploy(PATTERN)
        feed(svc.runtimes["DurPat"], frs[:4])
        svc.runtimes["DurPat"].persist()
        feed(svc.runtimes["DurPat"], frs[4:])
        svc.deploy(PATTERN)             # redeploy: recover, not park
        rt2 = svc.runtimes["DurPat"]
        assert rt2._wal_recovery["replayed_frames"] == 2
        assert table_rows(rt2, "M") == want
    finally:
        svc.stop()


def test_durability_without_store_disables_loudly():
    mgr = SiddhiManager()               # no persistence store, no dir
    env = os.environ.pop("SIDDHI_WAL_DIR", None)
    try:
        rt = mgr.create_app_runtime(APP)
        with pytest.warns(RuntimeWarning, match="DISABLED"):
            rt.start()
        assert rt.wal is None
        d = rt.statistics()["durability"]
        assert d["policy"] == "batch" and d["enabled"] is False
        assert "reason" in d
        ex = rt.explain()["durability"]
        assert ex["enabled"] is False and "reason" in ex
    finally:
        if env is not None:
            os.environ["SIDDHI_WAL_DIR"] = env
        mgr.shutdown()


def test_replay_feed_failure_captures_to_error_store(tmp_path):
    """Schema drift across a redeploy: a durable frame that cannot feed
    the new schema must land whole in the ErrorStore, never vanish."""
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frames(2))
    crash(mgr, rt)
    # the new schema ADDS a column the logged frames cannot provide
    APP2 = APP.replace("define stream S (sym string, p double);",
                       "define stream S (sym string, p double, v int);") \
              .replace("select sym, p insert into T;",
                       "select sym, p, v insert into T;") \
              .replace("define table T (sym string, p double);",
                       "define table T (sym string, p double, v int);")
    m2, rt2 = fresh(tmp_path, APP2)
    rep = rt2.recover()
    assert rep["failed_frames"] == 2 and rep["replayed_frames"] == 0
    ents = rt2.error_store.entries("S")
    assert len(ents) == 2 and ents[0].point == "wal.replay"
    m2.shutdown()


def test_wal_direct_api_roundtrip(tmp_path):
    """The WAL class on its own: append -> replay identity, watermark
    filter, metrics shape."""
    from siddhi_tpu.core.schema import StreamSchema, StringTable
    from siddhi_tpu.query.ast import Attribute, AttrType
    schema = StreamSchema("S", (Attribute("sym", AttrType.STRING),
                                Attribute("p", AttrType.DOUBLE)))
    strings = StringTable()
    wal = WriteAheadLog(str(tmp_path / "w"), policy="batch")
    for i in range(3):
        cols = {"sym": strings.encode_many(np.array([f"K{i}", "K0"])),
                "p": np.array([float(i), 0.5])}
        seq = wal.append("S", np.array([i, i], dtype=np.int64), cols,
                         strings, schema=schema)
        assert seq == i + 1
    wal.barrier()
    got = list(wal.replay())
    assert [g[1] for g in got] == [1, 2, 3]
    stream, seq, ts, cols = got[2]
    assert stream == "S" and cols["sym"].tolist() == ["K2", "K0"]
    assert cols["p"].tolist() == [2.0, 0.5]
    assert wal.watermark() == {"S": 3}
    wal.close()


# ---------------------------------------------------------------------------
# review-round regressions
# ---------------------------------------------------------------------------

def test_segments_stay_contiguous_after_scar_heal(tmp_path):
    """Healing past a mid-log scar must open the fresh segment
    CONTIGUOUSLY after the kept prefix — a numbering gap would read as
    corruption on the next open and quarantine (lose) everything
    appended after the heal."""
    frs = frames(6)
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frs[:3])
    rt.wal.rotate()                     # seal segment 1
    feed(rt, frs[3:])                   # segment 2
    crash(mgr, rt)
    sealed = _segs(tmp_path)[0]
    blob = bytearray(open(sealed, "rb").read())
    blob[10] ^= 0xFF                    # scar the FIRST segment
    open(sealed, "wb").write(bytes(blob))

    m2, rt2 = fresh(tmp_path)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rep2 = rt2.recover()            # seg 2 quarantined, prefix empty-ish
    feed(rt2, frames(2, seed=21))       # post-heal durable ingest
    post = table_rows(rt2, "T")
    crash(m2, rt2)
    # segment numbering on disk must be gap-free
    nums = [int(os.path.basename(s)[4:-4]) for s in _segs(tmp_path)]
    assert nums == list(range(nums[0], nums[0] + len(nums))), nums
    m3, rt3 = fresh(tmp_path)
    rep3 = rt3.recover()                # post-heal frames MUST survive
    assert rep3["corrupt_skipped"] == 0
    assert rep3["replayed_frames"] == rep2["replayed_frames"] + 2
    assert table_rows(rt3, "T") == post
    m3.shutdown()


def test_start_without_recover_replays_instead_of_truncating(tmp_path):
    """start() on a durable app with a pre-existing log runs the
    recovery manager itself: opening without replaying would let the
    next snapshot's watermark claim unapplied frames and truncate
    them — silent loss."""
    frs = frames(4)
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frs)
    want = table_rows(rt, "T")
    crash(mgr, rt)

    m2, rt2 = fresh(tmp_path)
    rt2.start()                         # no explicit recover()
    assert table_rows(rt2, "T") == want
    rev = rt2.persist()                 # truncation barrier is now safe
    crash(m2, rt2)
    m3, rt3 = fresh(tmp_path)
    rep = rt3.recover()
    assert rep["watermark"] == dict(rev.watermark)
    assert table_rows(rt3, "T") == want
    m3.shutdown()


def test_recover_is_idempotent_within_one_runtime(tmp_path):
    frs = frames(3)
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frs)
    crash(mgr, rt)
    m2, rt2 = fresh(tmp_path)
    rep1 = rt2.recover()
    rep2 = rt2.recover()                # second call: no double replay
    rt2.start()                         # and start() must not replay
    assert rep1["replayed_frames"] == 3
    assert rep2 == rep1
    assert table_rows(rt2, "T") == table_rows(rt2, "T")
    assert len(rt2.tables["T"].all_rows()) == 3 * 32
    m2.shutdown()


def test_recover_honors_manual_restore(tmp_path):
    """A caller that restored a PAST revision explicitly gets the WAL
    suffix past THAT watermark — recover() must not override their
    choice with the newest revision."""
    frs = frames(6)
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frs[:2])
    rev1 = rt.persist(asynchronous=True)    # async: no truncation
    rt.persistor().wait()
    feed(rt, frs[2:4])
    rt.persist(asynchronous=True)
    rt.persistor().wait()
    feed(rt, frs[4:])
    want = table_rows(rt, "T")
    crash(mgr, rt)

    m2, rt2 = fresh(tmp_path)
    rt2.restore_revision(str(rev1))         # the OLDER revision
    rep = rt2.recover()
    assert rep["restored_revision"] == str(rev1)
    assert rep["watermark"] == {"S": 2}
    assert rep["replayed_frames"] == 4      # suffix past revision 1
    assert table_rows(rt2, "T") == want
    m2.shutdown()


def test_replay_unknown_stream_captures_not_drops(tmp_path):
    """Durable frames of a stream the redeployed app no longer defines
    must land in the ErrorStore, not silently count as 'skipped'."""
    frs = frames(2)
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frs)
    crash(mgr, rt)
    APP2 = APP.replace("define stream S (sym string, p double);",
                       "define stream S2 (sym string, p double);") \
              .replace("from S select", "from S2 select")
    m2, rt2 = fresh(tmp_path, APP2)
    rep = rt2.recover()
    assert rep["failed_frames"] == 2 and rep["skipped_frames"] == 0
    ents = rt2.error_store.entries("S")
    assert len(ents) == 2 and ents[0].point == "wal.replay"
    assert ents[0].events                    # rows preserved whole
    m2.shutdown()


def test_direct_send_append_failure_captures_batch(tmp_path):
    """Row-path sends buffered before a failing freeze-time append must
    land in the ErrorStore (the builder was already cleared) — and only
    ONCE."""
    from siddhi_tpu.core.faults import FaultInjector, InjectedFault
    mgr, rt = fresh(tmp_path)
    rt.start()
    h = rt.input_handler("S")
    h.send(("A", 1.0))
    h.send(("B", 2.0))                  # buffered rows ride the freeze
    rt.fault_injector = FaultInjector(seed=1, counts={"wal.append": 1})
    with pytest.raises(InjectedFault):
        rt.flush()
    ents = rt.error_store.entries("S")
    assert len(ents) == 1 and ents[0].point == "wal.append"
    assert [tuple(r) for _t, r in ents[0].events] == [("A", 1.0),
                                                      ("B", 2.0)]
    rt.fault_injector = None
    rep = rt.error_store.replay(rt)     # replayable: nothing stranded
    assert rep["remaining"] == 0
    assert table_rows(rt, "T") == [("A", 1.0), ("B", 2.0)]
    mgr.shutdown()


def test_seq_floor_after_truncation_and_restart(tmp_path):
    """Snapshot-barrier truncation can empty the log; after a restart
    the seq counters must resume PAST the restored watermark, or the
    next recovery's skip would swallow brand-new durable frames."""
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frames(3))
    rev = rt.persist()                  # truncates everything <= {S: 3}
    crash(mgr, rt)

    m2, rt2 = fresh(tmp_path)
    rep = rt2.recover()                 # empty log, watermark {S: 3}
    assert rep["watermark"] == {"S": 3} and rep["replayed_frames"] == 0
    feed(rt2, frames(2, seed=77))       # new frames must number 4, 5
    assert rt2.wal.seqs == {"S": 5}
    want = table_rows(rt2, "T")
    crash(m2, rt2)

    m3, rt3 = fresh(tmp_path)
    rep3 = rt3.recover()
    assert rep3["replayed_frames"] == 2 and rep3["skipped_frames"] == 0
    assert table_rows(rt3, "T") == want
    m3.shutdown()


def test_shutdown_start_cycle_keeps_logging(tmp_path):
    """shutdown()+start() in one process must REOPEN the log (state is
    live, nothing replays) with seq continuity — and the enabled gauge
    must read 0 only while actually down."""
    mgr, rt = fresh(tmp_path)
    rt.start()
    feed(rt, frames(2))
    rt.shutdown()
    assert rt.statistics()["durability"]["enabled"] is False
    rt.start()                          # reopen, no replay into live state
    assert rt.wal is not None
    assert rt.statistics()["durability"]["enabled"] is True
    assert table_rows(rt, "T") == table_rows(rt, "T")
    feed(rt, frames(1, seed=31))
    assert rt.wal.seqs == {"S": 3}      # continuity past generation 1
    want = table_rows(rt, "T")
    crash(mgr, rt)
    m2, rt2 = fresh(tmp_path)
    rep = rt2.recover()
    assert rep["replayed_frames"] == 3
    assert table_rows(rt2, "T") == want
    m2.shutdown()


def test_durable_ack_waits_for_oldest_park(tmp_path):
    """Under shed.policy='oldest' the ACK must not cover memory-parked
    frames: the barrier drains the park (token refills) first, so by
    ACK time every frame is in the log."""
    from siddhi_tpu.net import TcpFrameClient
    app = ("@source(type='tcp', port='0', rate.limit='512', "
           "shed.policy='oldest', max.pending='8 MB')\n"
           + APP.replace("@app:name('Dur')", "@app:name('DurOld')"))
    mgr = SiddhiManager()
    mgr.set_persistence_store(FileSystemPersistenceStore(str(tmp_path)))
    rt = mgr.create_app_runtime(app)
    rt.start()
    cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "S",
                         TcpFrameClient.cols_of_schema(rt.schemas["S"]))
    frs = frames(4, batch=256)          # 1024 events vs a 512 burst:
    for cols, ts in frs:                # the tail parks
        cli.send_batch(cols, ts)
    cli.barrier(timeout=60)             # must wait out the park
    assert rt.wal.metrics()["appended_frames"] == 4
    assert rt.admission["S"].pending_count() == 0
    cli.close()
    mgr.shutdown()


def test_no_truncation_behind_inmemory_store(tmp_path):
    """A synchronous persist to an IN-MEMORY store must NOT truncate
    the on-disk log: the revision dies with the process, so the
    segments it would supersede are the only durable copy."""
    from siddhi_tpu.core.runtime import InMemoryPersistenceStore
    mgr = SiddhiManager()
    mgr.set_persistence_store(InMemoryPersistenceStore())
    app = APP.replace("@app:durability('batch')",
                      f"@app:durability('batch', dir='{tmp_path}/w')")
    rt = mgr.create_app_runtime(app)
    rt.start()
    feed(rt, frames(3))
    rt.wal.rotate()
    rev = rt.persist()                  # snapshot lives only in memory
    assert rev.watermark == {"S": 3}
    assert rt.wal.truncated_segments == 0
    want = table_rows(rt, "T")
    crash(mgr, rt)                      # process gone -> snapshot gone

    m2 = SiddhiManager()
    m2.set_persistence_store(InMemoryPersistenceStore())
    rt2 = m2.create_app_runtime(app)
    rep = rt2.recover()                 # full-log replay, nothing lost
    assert rep["restored_revision"] is None
    assert rep["replayed_frames"] == 3
    assert table_rows(rt2, "T") == want
    m2.shutdown()


# ---------------------------------------------------------------------------
# aggregation exactly-once (device-resident bucket state across recovery)
# ---------------------------------------------------------------------------

AGG_APP = """
@app:name('DurAgg')
@app:durability('batch')
define stream S (sym string, p double, ts long);
define aggregation Agg
from S
select sym, sum(p) as total, avg(p) as mean, count() as n
group by sym
aggregate by ts every sec, min;
"""

AGG_QUERY = ("from Agg within 1700000000000L, 1700000600000L per 'sec' "
             "select sym, total, mean, n")


def agg_frames(n_frames=6, batch=32, seed=9):
    rng = np.random.default_rng(seed)
    ts0 = 1_700_000_000_000
    out = []
    for k in range(n_frames):
        ts = ts0 + np.arange(k * batch, (k + 1) * batch,
                             dtype=np.int64) * 40
        out.append(({"sym": np.array([f"K{i}" for i in
                                      rng.integers(0, 5, batch)]),
                     "p": rng.uniform(90, 130, batch),
                     "ts": ts}, ts))
    return out


def agg_state(rt):
    return rt.aggregations["Agg"].state_dict()


def test_agg_recover_without_snapshot_rebuilds_buckets(tmp_path):
    """Full-log replay reconstructs the device-resident bucket store
    byte-identically (f64 merge order is deterministic)."""
    frs = agg_frames()
    mgr, rt = fresh(tmp_path, app=AGG_APP)
    rt.start()
    feed(rt, frs)
    want = agg_state(rt)
    want_rows = rt.query(AGG_QUERY)
    assert rt.explain()["aggregations"]["Agg"]["path"] == "device-resident"
    crash(mgr, rt)

    m2, rt2 = fresh(tmp_path, app=AGG_APP)
    rep = rt2.recover()
    assert rep["restored_revision"] is None
    assert rep["replayed_frames"] == len(frs)
    assert agg_state(rt2) == want
    assert rt2.query(AGG_QUERY) == want_rows
    m2.shutdown()


def test_agg_snapshot_plus_suffix_replay_exactly_once(tmp_path):
    """Snapshot mid-stream (simulated kill-9 after more ingest): the
    restored revision carries the pre-watermark buckets, replay merges
    ONLY the suffix — no double-counted and no lost contributions."""
    frs = agg_frames(8)
    mgr, rt = fresh(tmp_path, app=AGG_APP)
    rt.start()
    feed(rt, frs[:5])
    rev = rt.persist()
    assert rev.watermark == {"S": 5}
    feed(rt, frs[5:])
    want = agg_state(rt)
    want_rows = rt.query(AGG_QUERY)
    crash(mgr, rt)

    m2, rt2 = fresh(tmp_path, app=AGG_APP)
    rep = rt2.recover()
    assert rep["restored_revision"] == str(rev)
    assert rep["replayed_frames"] == 3
    assert agg_state(rt2) == want
    assert rt2.query(AGG_QUERY) == want_rows
    # double-recovery stays idempotent for bucket state too
    rep2 = rt2.recover()                # cached report, no double replay
    assert rep2 == rep
    assert agg_state(rt2) == want
    m2.shutdown()


def test_agg_recovery_parity_with_host_path(tmp_path):
    """The recovered device-resident store equals what a pure-host
    aggregation computes over the same frames (placement-independent
    durability)."""
    frs = agg_frames(5)
    mgr, rt = fresh(tmp_path, app=AGG_APP)
    rt.start()
    feed(rt, frs)
    crash(mgr, rt)
    m2, rt2 = fresh(tmp_path, app=AGG_APP)
    rt2.recover()
    got = rt2.query(AGG_QUERY)
    m2.shutdown()

    host_app = AGG_APP.replace("@app:durability('batch')\n",
                               "@app:deviceAggregations('off')\n")
    m3 = SiddhiManager()
    rt3 = m3.create_app_runtime(host_app)
    rt3.start()
    feed(rt3, frs)
    assert rt3.explain()["aggregations"]["Agg"]["path"] == "host"
    assert rt3.query(AGG_QUERY) == got
    m3.shutdown()
