"""Columnar wire frame protocol (siddhi_tpu/net/frame.py): encode/
decode round trips, checksum/truncation detection, schema negotiation,
string-table deltas and the connection-code remap."""
import struct
import zlib

import numpy as np
import pytest

from siddhi_tpu.core.schema import StreamSchema, StringTable
from siddhi_tpu.net import frame as fp
from siddhi_tpu.query.ast import Attribute, AttrType

SCHEMA = StreamSchema("S", (Attribute("sym", AttrType.STRING),
                            Attribute("p", AttrType.DOUBLE),
                            Attribute("v", AttrType.INT)))


def _stream_reader(blob: bytes):
    pos = [0]

    def read_exact(n):
        if pos[0] + n > len(blob):
            raise EOFError("eof")
        out = blob[pos[0]:pos[0] + n]
        pos[0] += n
        return out
    return read_exact


def test_frame_roundtrip_all_types():
    cases = [
        (fp.HELLO, fp.encode_hello("A", "S", [("sym", "string")])),
        (fp.HELLO_OK, fp.encode_hello_ok(64)),
        (fp.CREDIT, fp.encode_credit(17)),
        (fp.ACK, fp.encode_ack(5)),
        (fp.PING, fp.encode_ping(5)),
        (fp.ERROR, fp.encode_error("boom")),
        (fp.BYE, fp.encode_frame(fp.BYE)),
        (fp.STRINGS, fp.encode_strings(["a", "b"], start_code=1)),
    ]
    for want_type, blob in cases:
        ftype, payload = fp.read_frame(_stream_reader(blob))
        assert ftype == want_type
        # and via the buffer parser (ring/ws path)
        frames, rest = fp.parse_buffer(blob)
        assert rest == b"" and frames[0][0] == want_type


def test_parse_buffer_multiple_and_partial():
    blob = fp.encode_ack(1) + fp.encode_ack(2) + fp.encode_ack(3)
    frames, rest = fp.parse_buffer(blob + blob[:5])
    assert [fp.decode_u64(p) for _, p in frames] == [1, 2, 3]
    assert rest == blob[:5]


def test_checksum_mismatch_detected():
    blob = bytearray(fp.encode_credit(9))
    blob[-5] ^= 0xFF                      # flip a payload byte
    # stream path: strict (the receiver consumed an exact frame)
    with pytest.raises(fp.FrameError, match="checksum"):
        fp.read_frame(_stream_reader(bytes(blob)))
    # buffer path: the frame was consumed whole by its length prefix,
    # so it comes back as (ftype, None) — rejected without losing the
    # stream alignment — and the NEXT frame still parses
    frames, rest = fp.parse_buffer(bytes(blob) + fp.encode_ack(5))
    assert rest == b""
    assert frames[0] == (fp.CREDIT, None)
    assert frames[1][0] == fp.ACK and fp.decode_u64(frames[1][1]) == 5


def test_bad_magic_and_version():
    blob = fp.encode_ack(1)
    with pytest.raises(fp.FrameDesync, match="magic"):
        fp.read_frame(_stream_reader(b"XX" + blob[2:]))
    bad_ver = bytearray(blob)
    bad_ver[2] = 99
    with pytest.raises(fp.FrameDesync, match="version"):
        fp.read_frame(_stream_reader(bytes(bad_ver)))
    with pytest.raises(fp.FrameDesync, match="magic"):
        fp.parse_buffer(b"XX" + blob[2:])


def test_data_roundtrip_zero_copy_views():
    ts = np.arange(4, dtype=np.int64) + 1000
    sym = np.array([1, 2, 1, 3], dtype=np.int32)
    p = np.array([1.5, 2.5, 3.5, 4.5])
    v = np.array([7, 8, 9, 10], dtype=np.int32)
    blob = fp.encode_data(ts, [sym, p, v])
    ftype, payload = fp.read_frame(_stream_reader(blob))
    assert ftype == fp.DATA
    got_ts, cols = fp.decode_data(payload, SCHEMA)
    np.testing.assert_array_equal(got_ts, ts)
    np.testing.assert_array_equal(cols["sym"], sym)
    np.testing.assert_array_equal(cols["p"], p)
    np.testing.assert_array_equal(cols["v"], v)
    # views alias the payload (zero-copy) and are read-only
    assert cols["p"].base is not None
    assert not cols["p"].flags.writeable


def test_data_truncation_and_trailing_garbage():
    ts = np.arange(4, dtype=np.int64)
    blob = fp.encode_data(ts, [np.zeros(4, np.int32),
                               np.zeros(4), np.zeros(4, np.int32)])
    _, payload = fp.read_frame(_stream_reader(blob))
    with pytest.raises(fp.FrameError, match="truncated"):
        fp.decode_data(payload[:20], SCHEMA)
    with pytest.raises(fp.FrameError, match="trailing"):
        fp.decode_data(payload + b"\x00\x00", SCHEMA)


def test_hello_schema_negotiation():
    ok = fp.decode_hello(fp.read_frame(_stream_reader(
        fp.encode_hello("A", "S", [("sym", "string"), ("p", "double"),
                                   ("v", "int")])))[1])
    fp.validate_hello_schema(ok, SCHEMA)      # no raise
    bad = dict(ok, cols=[["sym", "string"], ["p", "float"], ["v", "int"]])
    with pytest.raises(fp.FrameError, match="schema mismatch"):
        fp.validate_hello_schema(bad, SCHEMA)
    with pytest.raises(fp.FrameError, match="schema mismatch"):
        fp.validate_hello_schema(dict(ok, cols=ok["cols"][:2]), SCHEMA)


def test_strings_delta_and_remap():
    wire = fp.WireStringTable()
    codes1, new1 = wire.encode_column(np.array(["a", "b", "a"]))
    assert new1 == ["a", "b"]
    np.testing.assert_array_equal(codes1, [1, 2, 1])
    codes2, new2 = wire.encode_column(np.array(["b", "c"]))
    assert new2 == ["c"]
    np.testing.assert_array_equal(codes2, [2, 3])

    table = StringTable()
    table.encode("preexisting")               # server table not empty
    remap = fp.StringRemap()
    remap.extend(1, new1, table)
    remap.extend(3, new2, table)
    got = remap.apply(np.array([1, 2, 3, 0], dtype=np.int32))
    assert [table.decode(int(c)) for c in got] == ["a", "b", "c", None]


def test_remap_gap_rejected():
    remap = fp.StringRemap()
    with pytest.raises(fp.FrameError, match="lost delta"):
        remap.extend(5, ["x"], StringTable())


def test_remap_overlap_idempotent():
    table = StringTable()
    remap = fp.StringRemap()
    remap.extend(1, ["a", "b"], table)
    remap.extend(1, ["a", "b", "c"], table)   # full-table replay overlap
    got = remap.apply(np.array([1, 2, 3], dtype=np.int32))
    assert [table.decode(int(c)) for c in got] == ["a", "b", "c"]


def test_remap_undeclared_code_rejected():
    remap = fp.StringRemap()
    remap.extend(1, ["a"], StringTable())
    with pytest.raises(fp.FrameError, match="never declared"):
        remap.apply(np.array([7], dtype=np.int32))


def test_strings_frame_roundtrip_unicode():
    blob = fp.encode_strings(["héllo", "wörld", ""], start_code=4)
    _, payload = fp.read_frame(_stream_reader(blob))
    assert fp.decode_strings(payload) == (4, ["héllo", "wörld", ""])


def test_worked_hex_example_matches_spec():
    """The docs/SERVING.md worked example: a 2-row DATA frame for
    (sym string, p double, v int) — pin the exact bytes so the spec
    and the implementation cannot drift apart silently."""
    ts = np.array([1000, 1001], dtype=np.int64)
    blob = fp.encode_data(ts, [np.array([1, 2], dtype=np.int32),
                               np.array([1.5, 2.5]),
                               np.array([7, 8], dtype=np.int32)])
    assert blob[:2] == b"FS"                  # magic 0x5346 LE
    assert blob[2] == 1 and blob[3] == fp.DATA
    (n,) = struct.unpack_from("<I", blob, 4)
    payload = blob[8:8 + n]
    assert payload[:4] == b"\x02\x00\x00\x00"         # n_rows = 2
    assert payload[4:12] == struct.pack("<q", 1000)   # first ts
    (crc,) = struct.unpack_from("<I", blob, 8 + n)
    assert crc == (zlib.crc32(payload) & 0xFFFFFFFF)


def test_ws_frame_oversize_declared_length_desyncs():
    """A ws header declaring a payload beyond the protocol's 64 MiB
    bound must fail loudly instead of growing the receive buffer
    forever while the scanner waits for bytes that never complete."""
    buf = bytearray(bytes([0x82, 127]) + struct.pack(">Q", 1 << 40))
    with pytest.raises(fp.FrameDesync):
        fp.parse_ws_frame_inplace(buf)
    # at-the-bound messages still parse (one protocol frame + header)
    ok = bytearray(bytes([0x82, 126]) + struct.pack(">H", 3) + b"abc")
    assert fp.parse_ws_frame_inplace(ok) == (0x2, b"abc")


# ---------------------------------------------------------------------------
# store-query frames (QUERY / RESULT)
# ---------------------------------------------------------------------------

def test_query_frame_roundtrip():
    blob = fp.encode_query(9, "from T select v", app="Dash")
    ftype, payload = fp.read_frame(_stream_reader(blob))
    assert ftype == fp.QUERY
    assert fp.decode_query(payload) == (9, "Dash", "from T select v")
    # app omitted -> None (the connection's HELLO-bound app serves)
    _, p2 = fp.read_frame(_stream_reader(fp.encode_query(1, "from T select v")))
    assert fp.decode_query(p2) == (1, None, "from T select v")


def test_query_frame_rejects_garbage():
    with pytest.raises(fp.FrameError, match="truncated"):
        fp.decode_query(b"\x00" * 4)
    with pytest.raises(fp.FrameError, match="truncated"):
        fp.decode_query(struct.pack("<QH", 1, 99) + b"xy")
    with pytest.raises(fp.FrameError, match="empty QUERY"):
        fp.decode_query(struct.pack("<QH", 1, 0) + b"   ")


def test_result_frame_roundtrip_with_body():
    cols = [["sym", "string"], ["total", "double"], ["n", "long"]]
    body = fp.encode_data_payload(
        np.array([1000, 2000], dtype=np.int64),
        [np.array([1, 2], dtype=np.int32),
         np.array([10.25, 3.5]),
         np.array([2, 1], dtype=np.int64)])
    blob = fp.encode_result(5, {"cols": cols}, body)
    ftype, payload = fp.read_frame(_stream_reader(blob))
    assert ftype == fp.RESULT
    token, meta, got_body = fp.decode_result(payload)
    assert token == 5 and meta == {"cols": cols} and got_body == body
    ts, views = fp.decode_result_body(got_body, cols)
    assert ts.tolist() == [1000, 2000]
    assert views[0].dtype == np.int32 and views[0].tolist() == [1, 2]
    # doubles are ALWAYS float64 on the result plane
    assert views[1].dtype == np.float64 and views[1].tolist() == [10.25, 3.5]
    assert views[2].dtype == np.int64 and views[2].tolist() == [2, 1]


def test_result_frame_error_meta():
    blob = fp.encode_result(3, {"error": "no such aggregation"})
    _, payload = fp.read_frame(_stream_reader(blob))
    token, meta, body = fp.decode_result(payload)
    assert token == 3 and meta["error"] == "no such aggregation"
    assert body == b""


def test_result_body_rejects_malformed():
    cols = [["v", "double"]]
    good = fp.encode_data_payload(np.array([1], dtype=np.int64),
                                  [np.array([1.5])])
    with pytest.raises(fp.FrameError, match="truncated"):
        fp.decode_result_body(good[:-3], cols)
    with pytest.raises(fp.FrameError, match="trailing"):
        fp.decode_result_body(good + b"\x00", cols)
    with pytest.raises(fp.FrameError, match="unknown type"):
        fp.decode_result_body(good, [["v", "wat"]])
    with pytest.raises(fp.FrameError, match="truncated"):
        fp.decode_result(b"\x00" * 6)


def test_query_result_worked_hex_example_matches_spec():
    """The docs/SERVING.md store-query worked example: pin the exact
    bytes of a QUERY frame and its 1-row RESULT so the spec and the
    implementation cannot drift apart silently."""
    q = fp.encode_query(7, "from T select v", app="Dash")
    assert q[:2] == b"FS" and q[2] == 1 and q[3] == fp.QUERY
    (n,) = struct.unpack_from("<I", q, 4)
    qp = q[8:8 + n]
    assert qp[:8] == struct.pack("<Q", 7)             # token
    assert qp[8:10] == b"\x04\x00"                    # app_len = 4
    assert qp[10:14] == b"Dash"
    assert qp[14:] == b"from T select v"
    (crc,) = struct.unpack_from("<I", q, 8 + n)
    assert crc == (zlib.crc32(qp) & 0xFFFFFFFF)

    body = fp.encode_data_payload(np.array([1000], dtype=np.int64),
                                  [np.array([2.5])])
    r = fp.encode_result(7, {"cols": [["v", "double"]]}, body)
    assert r[:2] == b"FS" and r[3] == fp.RESULT
    (n,) = struct.unpack_from("<I", r, 4)
    rp = r[8:8 + n]
    assert rp[:8] == struct.pack("<Q", 7)             # token echoes
    (mlen,) = struct.unpack_from("<I", rp, 8)
    assert rp[12:12 + mlen] == b'{"cols": [["v", "double"]]}'
    assert rp[12 + mlen:12 + mlen + 4] == b"\x01\x00\x00\x00"  # n_rows
    assert rp[12 + mlen + 4:12 + mlen + 12] == struct.pack("<q", 1000)
    assert rp[12 + mlen + 12:] == struct.pack("<d", 2.5)
