"""Aggregator x window correctness matrix (reference: siddhi-core
query/selector/attribute/aggregator tests + window tests, VERDICT r3 #8).

Two oracles:
 * an independent numpy/python simulation of sliding/tumbling window
   aggregation validates the HOST engine for all 12 aggregators;
 * the host engine then validates the DEVICE window-agg plan for the
   device-supported aggregators (sum/count/avg/min/max) across window
   kinds and group-by shapes."""
import math
import random

import pytest

from siddhi_tpu import SiddhiManager

HEAD = "@app:playback define stream S (sym string, p double, v long);\n"


def gen_rows(n, n_syms=3, seed=1):
    r = random.Random(seed)
    ts = 1000
    rows = []
    for _ in range(n):
        ts += r.randint(0, 300)
        rows.append((ts, (f"s{r.randint(0, n_syms - 1)}",
                          round(r.uniform(-40, 120), 2), r.randint(1, 9))))
    return rows


def run_engine(app, rows, batch=5):
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    out = []
    rt.add_callback("O", lambda evs: out.extend(
        (e.timestamp, tuple(e.data)) for e in evs))
    h = rt.input_handler("S")
    rt.start()
    for i, (ts, row) in enumerate(rows):
        h.send(row, timestamp=ts)
        if (i + 1) % batch == 0:
            rt.flush()
    rt.flush()
    m.shutdown()
    return out


# -- python window-aggregation oracle ---------------------------------------

def oracle_sliding_length(rows, L, agg, arg, group):
    """Per-event aggregate over the last L events: ONE shared window;
    `group by` aggregates the arriving event's group WITHIN it
    (reference: window retention is per-window, grouping is selector-
    level — QuerySelector group-by over the shared window state)."""
    out = []
    buf: list = []
    for ts, row in rows:
        sym, p, v = row
        buf.append(row)
        if len(buf) > L:
            buf.pop(0)
        if group:
            mine = [r for r in buf if r[0] == sym]
            out.append((ts, (sym, _agg_of(mine, agg, arg))))
        else:
            out.append((ts, (_agg_of(buf, agg, arg),)))
    return out


def _agg_of(buf, agg, arg):
    vals = [r[1] if arg == "p" else r[2] for r in buf]
    if agg == "sum":
        s = sum(vals)
        return float(s) if arg == "p" else int(s)
    if agg == "count":
        return len(vals)
    if agg == "avg":
        return sum(vals) / len(vals)
    if agg == "min":
        return min(vals)
    if agg == "max":
        return max(vals)
    if agg == "minForever" or agg == "maxForever":
        raise NotImplementedError
    if agg == "stdDev":
        mu = sum(vals) / len(vals)
        return math.sqrt(sum((x - mu) ** 2 for x in vals) / len(vals))
    if agg == "distinctCount":
        return len(set(vals))
    if agg == "and":
        return all(v > 0 for v in vals)
    if agg == "or":
        return any(v > 5 for v in vals)
    raise KeyError(agg)


SIM_AGGS = {
    "sum": "sum(p) as r", "count": "count() as r", "avg": "avg(p) as r",
    "min": "min(p) as r", "max": "max(p) as r",
    "stdDev": "stdDev(p) as r", "distinctCount": "distinctCount(v) as r",
}


@pytest.mark.parametrize("agg", list(SIM_AGGS))
@pytest.mark.parametrize("group", [False, True])
def test_host_engine_matches_python_oracle(agg, group):
    rows = gen_rows(60, seed=hash(agg) % 1000 + group)
    sel = SIM_AGGS[agg]
    gb = "group by sym " if group else ""
    q = (f"@info(name='q') from S#window.length(5) select "
         f"{'sym, ' if group else ''}{sel} {gb}insert into O;")
    got = run_engine("@app:deviceWindows('never')\n" + HEAD + q, rows)
    arg = "v" if agg == "distinctCount" else "p"
    want = oracle_sliding_length(rows, 5, agg, arg, group)
    assert len(got) == len(want)
    for (gts, grow), (wts, wrow) in zip(got, want):
        assert gts == wts
        for a, b in zip(grow, wrow):
            if isinstance(b, float):
                assert a == pytest.approx(b, rel=1e-6, abs=1e-4), (agg, got)
            else:
                assert a == b, (agg, grow, wrow)


def test_forever_aggregators_never_expire():
    rows = gen_rows(40, seed=7)
    q = ("@info(name='q') from S#window.length(3) select "
         "minForever(p) as lo, maxForever(p) as hi insert into O;")
    got = run_engine("@app:deviceWindows('never')\n" + HEAD + q, rows)
    lo = hi = None
    for (ts, row), (_t, (sym, p, v)) in zip(got, rows):
        lo = p if lo is None else min(lo, p)
        hi = p if hi is None else max(hi, p)
        assert row[0] == pytest.approx(lo) and row[1] == pytest.approx(hi)


def test_and_or_aggregators():
    rows = [(1000 + i, ("s0", 1.0, i % 3)) for i in range(12)]
    q = ("@info(name='q') from S#window.length(4) select "
         "and(v > 0) as allpos, or(v > 1) as anybig insert into O;")
    got = run_engine("@app:deviceWindows('never')\n" + HEAD + q, rows)
    win: list = []
    for (ts, row), (_t, (_s, _p, v)) in zip(got, rows):
        win.append(v)
        if len(win) > 4:
            win.pop(0)
        assert row == (all(x > 0 for x in win), any(x > 1 for x in win))


def test_union_set_aggregator():
    rows = [(1000 + i, (f"s{i % 3}", float(i), 1)) for i in range(9)]
    q = ("@info(name='q') from S#window.lengthBatch(3) select "
         "unionSet(createSet(sym)) as syms insert into O;")
    got = run_engine("@app:deviceWindows('never')\n" + HEAD + q, rows)
    # rows emit per event with the running set; each completed 3-event
    # bucket's LAST row carries the full set of its bucket's symbols
    assert [row[0] for _ts, row in got[2::3]] == [
        {"s0", "s1", "s2"}] * 3
    assert len(got) == 9


# -- device window-agg differential breadth ---------------------------------

DEV_CASES = []
for w in ["length(7)", "time(1 sec)", "lengthBatch(4)"]:
    for agg in ["sum(p) as r", "count() as r", "avg(p) as r",
                "min(p) as r1, max(p) as r2"]:
        for gb in ["", "group by sym "]:
            if gb and "min" in agg and "Batch" not in w:
                continue        # grouped sliding min/max is host-only
            DEV_CASES.append((w, agg, gb))


@pytest.mark.parametrize("wi", range(len(DEV_CASES)))
def test_device_window_agg_differential(wi):
    w, agg, gb = DEV_CASES[wi]
    sel = ("sym, " if gb else "") + agg
    q = (f"@info(name='q') from S#window.{w} select {sel} {gb}"
         f"insert into O;")
    rows = gen_rows(70, seed=wi + 100)
    dev = run_engine("@app:deviceWindows('always')\n" + HEAD + q, rows)
    host = run_engine("@app:deviceWindows('never')\n" + HEAD + q, rows)
    assert len(dev) == len(host), (w, agg, gb, len(dev), len(host))
    for (dts, drow), (hts, hrow) in zip(dev, host):
        assert dts == hts
        for a, b in zip(drow, hrow):
            if isinstance(b, float):
                assert a == pytest.approx(b, rel=2e-5, abs=2e-4), (w, agg)
            else:
                assert a == b, (w, agg, gb, drow, hrow)


# -- having / order-by / limit over aggregates ------------------------------

def test_having_filters_aggregate_rows():
    rows = gen_rows(40, seed=3)
    q = ("@info(name='q') from S#window.length(5) select sym, sum(p) as s "
         "group by sym having s > 100.0 insert into O;")
    dev = run_engine("@app:deviceWindows('always')\n" + HEAD + q, rows)
    host = run_engine("@app:deviceWindows('never')\n" + HEAD + q, rows)
    assert len(dev) == len(host)
    for (dts, drow), (hts, hrow) in zip(dev, host):
        assert dts == hts and drow[0] == hrow[0]
        assert drow[1] == pytest.approx(hrow[1], rel=2e-5)  # device f32
    for _ts, (sym, s) in host:
        assert s > 100.0


def test_order_by_limit_on_batch():
    rows = [(1000 + i, (f"s{i % 4}", float(10 - i % 7), 1))
            for i in range(16)]
    q = ("@info(name='q') from S#window.lengthBatch(8) select sym, "
         "sum(p) as s group by sym order by s desc limit 2 insert into O;")
    got = run_engine("@app:deviceWindows('never')\n" + HEAD + q, rows)
    by_batch: dict = {}
    for ts, row in got:
        by_batch.setdefault(ts, []).append(row)
    for rows_ in by_batch.values():
        ss = [r[1] for r in rows_]
        assert ss == sorted(ss, reverse=True) and len(rows_) <= 2
