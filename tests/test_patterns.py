"""Pattern/sequence (CEP NFA) integration tests — sequential backend.

Mirrors reference expectations (reference: modules/siddhi-core/src/test/.../
query/pattern/{EveryPattern,PatternCount,LogicalPattern,AbsentPattern}TestCase.java
and query/sequence/*)."""
import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def collect(rt, stream):
    got = []
    rt.add_callback(stream, lambda evs: got.extend(evs))
    return got


def test_simple_pattern(mgr):
    rt = mgr.create_app_runtime("""
        define stream Stock (symbol string, price double);
        from e1=Stock[price > 100] -> e2=Stock[price > e1.price]
        select e1.price as p1, e2.price as p2 insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("Stock")
    h.send(("A", 101.0))
    h.send(("A", 50.0))     # irrelevant (pattern skips)
    h.send(("A", 102.5))    # completes
    h.send(("A", 200.0))    # no every -> no more matches
    rt.flush()
    assert [e.data for e in got] == [(101.0, 102.5)]


def test_every_pattern(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (sym string, p double);
        from every e1=S[p > 100] -> e2=S[p > e1.p]
        select e1.p as p1, e2.p as p2 insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    for p in [101.0, 102.0, 103.0]:
        h.send(("A", p))
    rt.flush()
    # every arms a new e1 per event>100; each armed partial is consumed by
    # its first completing e2: (101,102) then (102,103)
    datas = sorted(e.data for e in got)
    assert datas == [(101.0, 102.0), (102.0, 103.0)]


def test_pattern_within(mgr):
    rt = mgr.create_app_runtime("""
        @app:playback
        define stream S (p double);
        from every e1=S[p > 100] -> e2=S[p > e1.p] within 1 sec
        select e1.p as p1, e2.p as p2 insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    h.send((101.0,), timestamp=1000)
    h.send((150.0,), timestamp=2500)   # too late for e1=101 (within 1 sec)
    h.send((200.0,), timestamp=3000)   # completes e1=150
    rt.flush()
    assert [e.data for e in got] == [(150.0, 200.0)]


def test_pattern_across_streams(mgr):
    rt = mgr.create_app_runtime("""
        define stream A (x int);
        define stream B (y int);
        from e1=A[x > 0] -> e2=B[y > e1.x]
        select e1.x as x, e2.y as y insert into O;
    """)
    got = collect(rt, "O")
    ha, hb = rt.input_handler("A"), rt.input_handler("B")
    ha.send((5,))
    hb.send((3,))     # y not > 5
    hb.send((7,))     # completes
    rt.flush()
    assert [e.data for e in got] == [(5, 7)]


def test_pattern_count(mgr):
    rt = mgr.create_app_runtime("""
        define stream T (temp double);
        from e1=T[temp > 30]<2:3> -> e2=T[temp < 10]
        select e1[0].temp as t0, e1[1].temp as t1, e2.temp as tl
        insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("T")
    h.send((31.0,))
    h.send((32.0,))
    h.send((5.0,))
    rt.flush()
    assert [e.data for e in got] == [(31.0, 32.0, 5.0)]


def test_logical_and_pattern(mgr):
    rt = mgr.create_app_runtime("""
        define stream A (x int);
        define stream B (y int);
        define stream C (z int);
        from e1=A and e2=B -> e3=C
        select e1.x as x, e2.y as y, e3.z as z insert into O;
    """)
    got = collect(rt, "O")
    ha, hb, hc = (rt.input_handler(s) for s in "ABC")
    hb.send((2,))
    hc.send((9,))     # C before A+B complete: ignored
    ha.send((1,))
    hc.send((3,))
    rt.flush()
    assert [e.data for e in got] == [(1, 2, 3)]


def test_logical_or_pattern(mgr):
    rt = mgr.create_app_runtime("""
        define stream A (x int);
        define stream B (y int);
        from e1=A or e2=B select e1.x as x, e2.y as y insert into O;
    """)
    got = collect(rt, "O")
    hb = rt.input_handler("B")
    hb.send((42,))
    rt.flush()
    # e1 absent -> real null in decoded output
    assert [e.data for e in got] == [(None, 42)]


def test_absent_pattern_timer(mgr):
    rt = mgr.create_app_runtime("""
        @app:playback
        define stream A (x int);
        define stream B (y int);
        from e1=A -> not B for 1 sec
        select e1.x as x insert into O;
    """)
    got = collect(rt, "O")
    ha = rt.input_handler("A")
    ha.send((7,), timestamp=1000)
    rt.flush()
    assert got == []
    rt.set_time(2100)        # deadline 2000 passed, no B
    assert [e.data for e in got] == [(7,)]


def test_absent_pattern_suppressed_by_event(mgr):
    rt = mgr.create_app_runtime("""
        @app:playback
        define stream A (x int);
        define stream B (y int);
        from e1=A -> not B for 1 sec
        select e1.x as x insert into O;
    """)
    got = collect(rt, "O")
    ha, hb = rt.input_handler("A"), rt.input_handler("B")
    ha.send((7,), timestamp=1000)
    hb.send((1,), timestamp=1500)   # B arrives within the window -> no match
    rt.flush()
    rt.set_time(3000)
    assert got == []


def test_absent_and_present(mgr):
    rt = mgr.create_app_runtime("""
        define stream R (t double);
        define stream T (t double);
        define stream H (h double);
        from e1=R -> not T[t > e1.t] and e2=H
        select e1.t as rt_, e2.h as h insert into O;
    """)
    got = collect(rt, "O")
    hr, ht, hh = rt.input_handler("R"), rt.input_handler("T"), rt.input_handler("H")
    hr.send((20.0,))
    hh.send((55.0,))
    rt.flush()
    assert [e.data for e in got] == [(20.0, 55.0)]
    # second round: T fires first -> suppressed
    hr.send((30.0,))
    rt.flush()
    ht.send((35.0,))
    hh.send((60.0,))
    rt.flush()
    assert len(got) == 1


def test_sequence_strict(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (sym string, p double);
        from every e1=S[p > 100], e2=S[p > e1.p]
        select e1.p as p1, e2.p as p2 insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    h.send(("A", 101.0))
    h.send(("A", 50.0))    # breaks contiguity for pending e1=101
    h.send(("A", 102.0))
    h.send(("A", 103.0))   # completes e1=102 (every re-arms)
    rt.flush()
    assert [e.data for e in got] == [(102.0, 103.0)]


def test_sequence_plus(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (v int);
        from every e1=S[v > 0]+, e2=S[v == 0]
        select e1[0].v as first, e1[last].v as last_, e2.v as z insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    for v in [1, 2, 3, 0]:
        h.send((v,))
    rt.flush()
    # every arms at each positive; strict contiguity keeps runs: [1,2,3]0, [2,3]0, [3]0
    datas = sorted(e.data for e in got)
    assert (1, 3, 0) in datas
    assert (3, 3, 0) in datas


def test_pattern_select_star(mgr):
    rt = mgr.create_app_runtime("""
        define stream A (x int);
        define stream B (y int);
        from e1=A -> e2=B select * insert into O;
    """)
    got = collect(rt, "O")
    ha, hb = rt.input_handler("A"), rt.input_handler("B")
    ha.send((1,))
    hb.send((2,))
    rt.flush()
    assert [e.data for e in got] == [(1, 2)]


def test_pattern_snapshot_restore(mgr):
    app = """
        define stream S (p double);
        from e1=S[p > 100] -> e2=S[p > e1.p]
        select e1.p as p1, e2.p as p2 insert into O;
    """
    rt = mgr.create_app_runtime(app)
    h = rt.input_handler("S")
    h.send((101.0,))
    rt.flush()
    snap = rt.snapshot()

    rt2 = mgr.create_app_runtime(app.replace("define", "@app:name('x2') define", 1))
    got = collect(rt2, "O")
    rt2.restore(snap)
    h2 = rt2.input_handler("S")
    h2.send((150.0,))
    rt2.flush()
    assert [e.data for e in got] == [(101.0, 150.0)]
