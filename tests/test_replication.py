"""Hot-standby WAL replication (core/replication.py + net/repl.py +
core/wal.py tail/fencing): REPL frame round-trips, the tail's
rotation/truncation/scar edge cases, verbatim standby appends, the
semi-sync durable-ACK barrier, generation fencing, and the in-process
failover path (standby converges -> promote() -> byte-identical log +
replayed outputs)."""
import glob
import os
import threading
import time

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.persistence import FileSystemPersistenceStore
from siddhi_tpu.core.replication import (ReplicationConfig,
                                         ReplicationCoordinator,
                                         ReplicationError)
from siddhi_tpu.core.wal import (WalError, WriteAheadLog,
                                 read_generation, write_generation)
from siddhi_tpu.net import frame as fp
from siddhi_tpu.net.client import NetClientError, TcpFrameClient
from siddhi_tpu.net.repl import ReplProtocolError, WalReceiver
from siddhi_tpu.net.server import NetServer

# transport/replication semantics are backend-independent: host-only
# apps skip every jit compile (same budget rationale as test_net_server)
HOST = ("@app:deviceFilters('never')\n@app:devicePatterns('never')\n"
        "@app:deviceWindows('never')\n")
BODY = """
define stream S (sym string, p double);
define table T (sym string, p double);
@info(name='ins') from S select sym, p insert into T;
@info(name='out') from S[p > 110.0] select sym, p insert into Out;
"""


def table_rows(rt, name="T"):
    return sorted(map(tuple, rt.tables[name].all_rows()))


def frames(n_frames=5, batch=16, seed=7):
    rng = np.random.default_rng(seed)
    ts0 = 1_700_000_000_000
    return [({"sym": np.array([f"K{i}" for i in
                               rng.integers(0, 4, batch)]),
              "p": np.round(rng.uniform(90, 130, batch), 2)},
             ts0 + np.arange(k * batch, (k + 1) * batch, dtype=np.int64))
            for k in range(n_frames)]


def feed(rt, frs, stream="S"):
    h = rt.input_handler(stream)
    for cols, ts in frs:
        h.send_batch(cols, ts)
    rt.flush()


def wal_append(wal, stream, seq_hint, n=4):
    """Append one tiny frame; returns the assigned seq."""
    ts = np.arange(n, dtype=np.int64) + 1_700_000_000_000 + seq_hint * n
    cols = {"v": np.arange(n, dtype=np.float64) + seq_hint}
    return wal.append(stream, ts, cols, strings=None)


def drain(tail, max_polls=50):
    """Poll until caught up; -> (records, saw_gap)."""
    out, saw_gap = [], False
    for _ in range(max_polls):
        recs, gap = tail.poll()
        out.extend(recs)
        saw_gap = saw_gap or gap
        if not recs:
            break
    return out, saw_gap


def wait_for(pred, timeout_s=10.0, interval_s=0.01):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


# ---------------------------------------------------------------------------
# REPL frame round-trips
# ---------------------------------------------------------------------------

def test_repl_subscribe_roundtrip():
    blob = fp.encode_repl_subscribe("HA", {"S": 12, "Q": 3}, 7)
    frames_, = [fp.parse_buffer(bytes(blob))[0]]
    (ftype, payload), = frames_
    assert ftype == fp.REPL_SUBSCRIBE
    sub = fp.decode_repl_subscribe(payload)
    assert sub["app"] == "HA"
    assert sub["watermark"] == {"S": 12, "Q": 3}
    assert sub["generation"] == 7


def test_repl_record_roundtrip():
    raw = b"\x01\x02record-bytes\xff" * 3
    blob = fp.encode_repl_record(5, raw)
    (ftype, payload), = fp.parse_buffer(bytes(blob))[0]
    assert ftype == fp.REPL_RECORD
    gen, got = fp.decode_repl_record(payload)
    assert gen == 5 and got == raw


@pytest.mark.parametrize("final,wm", [(True, {"S": 9}), (False, None)])
def test_repl_snapshot_roundtrip(final, wm):
    blob = fp.encode_repl_snapshot(2, "F-HA-123", wm, b"\x00" * 64,
                                   final=final)
    (ftype, payload), = fp.parse_buffer(bytes(blob))[0]
    assert ftype == fp.REPL_SNAPSHOT
    gen, meta, body = fp.decode_repl_snapshot(payload)
    assert gen == 2 and body == b"\x00" * 64
    assert meta["revision"] == "F-HA-123"
    assert bool(meta["final"]) == final
    assert meta.get("watermark") == wm


def test_repl_status_frames_roundtrip():
    for enc, ft in ((fp.encode_repl_ack(3, {"S": 4}), fp.REPL_ACK),
                    (fp.encode_repl_heartbeat(3, {"S": 4}, 1234),
                     fp.REPL_HEARTBEAT)):
        (ftype, payload), = fp.parse_buffer(bytes(enc))[0]
        assert ftype == ft
        st = fp.decode_repl_status(payload)
        assert st["generation"] == 3 and st["watermark"] == {"S": 4}


# ---------------------------------------------------------------------------
# WAL tail edge cases (rotation, truncation, scars)
# ---------------------------------------------------------------------------

def test_tail_streams_live_appends(tmp_path):
    wal = WriteAheadLog(str(tmp_path), policy="batch")
    tail = wal.tail()
    assert tail.poll() == ([], False)       # empty log: caught up
    for i in range(3):
        wal_append(wal, "S", i)
    recs, gap = drain(tail)
    assert not gap
    assert [(s, q) for s, q, _ in recs] == [("S", 1), ("S", 2), ("S", 3)]
    wal_append(wal, "S", 3)                 # appended AFTER the drain
    recs, gap = drain(tail)
    assert not gap and [(s, q) for s, q, _ in recs] == [("S", 4)]
    wal.close()


def test_tail_follows_segment_rotation(tmp_path):
    wal = WriteAheadLog(str(tmp_path), policy="batch", segment_bytes=256)
    for i in range(12):
        wal_append(wal, "S", i)
    assert len(glob.glob(os.path.join(str(tmp_path), "wal-*.seg"))) > 2
    recs, gap = drain(wal.tail())
    assert not gap
    assert [q for _, q, _ in recs] == list(range(1, 13))
    wal.close()


def test_tail_from_watermark_skips_prefix(tmp_path):
    wal = WriteAheadLog(str(tmp_path), policy="batch")
    for i in range(6):
        wal_append(wal, "S", i)
    recs, gap = drain(wal.tail({"S": 4}))
    assert not gap and [q for _, q, _ in recs] == [5, 6]
    wal.close()


def test_tail_truncation_reports_gap_then_catchup_resumes(tmp_path):
    """Snapshot-barrier truncation beneath a fresh subscriber is a GAP
    (ship a Revision, advance_to, re-poll) — never an error, and the
    gap record is NOT consumed."""
    wal = WriteAheadLog(str(tmp_path), policy="batch", segment_bytes=256)
    for i in range(10):
        wal_append(wal, "S", i)
    wal.rotate()                            # seal everything appended
    deleted = wal.truncate({"S": 6})        # sealed segs wholly <= 6 go
    assert deleted > 0
    tail = wal.tail()                       # standby from NOTHING
    recs, gap = tail.poll()
    assert gap                              # records 1..k are gone
    # the snapshot chain covers <= its watermark; advance and re-poll
    tail.advance_to({"S": 6})
    recs, gap = drain(tail)
    assert not gap
    assert [q for _, q, _ in recs] == list(range(7, 11))
    wal.close()


def test_tail_never_ships_past_a_scar(tmp_path):
    """A CRC-scarred record parks the tail forever: everything before
    the scar ships, nothing after it ever does (replay could not apply
    it either — the scar is the heal boundary)."""
    wal = WriteAheadLog(str(tmp_path), policy="batch")
    for i in range(5):
        wal_append(wal, "S", i)
    tail = wal.tail()
    recs, _ = drain(tail)
    assert len(recs) == 5
    # corrupt record 3 of a SECOND tail's view: flip payload bytes
    seg = glob.glob(os.path.join(str(tmp_path), "wal-*.seg"))[0]
    boundaries = []
    data = open(seg, "rb").read()
    off = 0
    while True:
        rec = WriteAheadLog._parse_record(data, off)
        if rec is None:
            break
        boundaries.append((off, rec[3]))
        off = rec[3]
    start, _end = boundaries[2]
    with open(seg, "r+b") as f:
        f.seek(start + 20)
        f.write(b"\xde\xad\xbe\xef")
    scarred = wal.tail()
    recs, gap = drain(scarred)
    assert not gap
    assert [q for _, q, _ in recs] == [1, 2]    # parked AT the scar
    for _ in range(3):                          # and it STAYS parked
        assert scarred.poll() == ([], False)
    wal.close()


# ---------------------------------------------------------------------------
# append_raw: the standby's verbatim apply
# ---------------------------------------------------------------------------

def test_append_raw_byte_identical_and_idempotent(tmp_path):
    primary = WriteAheadLog(str(tmp_path / "p"), policy="batch")
    standby = WriteAheadLog(str(tmp_path / "s"), policy="batch")
    for i in range(4):
        wal_append(primary, "S", i)
    recs, _ = drain(primary.tail())
    for _stream, _seq, raw in recs:
        stream, seq, applied = standby.append_raw(raw)
        assert applied
    # re-ship (reconnect from an older ack): idempotent, not an error
    assert standby.append_raw(recs[0][2]) == ("S", 1, False)
    assert standby.watermark() == primary.watermark()
    primary.close(), standby.close()
    pb = b"".join(open(f, "rb").read() for f in
                  sorted(glob.glob(str(tmp_path / "p" / "wal-*.seg"))))
    sb = b"".join(open(f, "rb").read() for f in
                  sorted(glob.glob(str(tmp_path / "s" / "wal-*.seg"))))
    assert pb == sb and len(pb) > 0


def test_append_raw_gap_raises_loudly(tmp_path):
    primary = WriteAheadLog(str(tmp_path / "p"), policy="batch")
    standby = WriteAheadLog(str(tmp_path / "s"), policy="batch")
    for i in range(4):
        wal_append(primary, "S", i)
    recs, _ = drain(primary.tail())
    standby.append_raw(recs[0][2])
    with pytest.raises(WalError, match="replication gap.*snapshot"):
        standby.append_raw(recs[3][2])      # seq 4 after seq 1
    with pytest.raises(WalError, match="corrupt replicated record"):
        standby.append_raw(recs[1][2][:-3])
    primary.close(), standby.close()


# ---------------------------------------------------------------------------
# fencing: the generation token
# ---------------------------------------------------------------------------

def test_generation_persists_and_fence_is_monotonic(tmp_path):
    d = str(tmp_path)
    assert read_generation(d) == 0
    write_generation(d, 3)
    assert read_generation(d) == 3
    wal = WriteAheadLog(d, policy="batch")
    assert wal.generation() == 3
    assert wal.fence() == 4                 # past local
    assert wal.fence(10) == 11              # past the peer's too
    wal.close()
    assert read_generation(d) == 11         # durable across reopen


# ---------------------------------------------------------------------------
# coordinator: the semi-sync barrier + lag accounting
# ---------------------------------------------------------------------------

def test_config_validation():
    with pytest.raises(ReplicationError, match="unknown mode"):
        ReplicationConfig("sync")
    with pytest.raises(ReplicationError, match="requires peer"):
        ReplicationConfig("async", role="standby")
    with pytest.raises(ReplicationError, match="degrade"):
        ReplicationConfig("semi-sync", degrade="ignore")
    cfg = ReplicationConfig("semi-sync", peer="h:1", ack_timeout_s=0.5)
    assert cfg.to_dict()["mode"] == "semi-sync"


def test_wait_ack_returns_when_covered_and_times_out_otherwise():
    coord = ReplicationCoordinator(
        ReplicationConfig("semi-sync", ack_timeout_s=0.2))
    coord.standby_attached()
    coord.on_ack({"S": 5})
    assert coord.wait_ack({"S": 5}) is True       # already covered
    assert coord.wait_ack({"S": 9}) is False      # nobody acks: timeout
    assert coord.barrier_timeouts == 1
    # a concurrent ack wakes the sleeper before the deadline
    t = threading.Timer(0.05, coord.on_ack, args=({"S": 9},))
    t.start()
    assert coord.wait_ack({"S": 9}, timeout_s=2.0) is True
    t.join()


def test_wait_ack_no_standby_fails_unless_degraded():
    strict = ReplicationCoordinator(
        ReplicationConfig("semi-sync", ack_timeout_s=0.1))
    assert strict.wait_ack({"S": 1}) is False     # no standby: FAIL
    lax = ReplicationCoordinator(
        ReplicationConfig("semi-sync", ack_timeout_s=0.1,
                          degrade="async"))
    assert lax.wait_ack({"S": 1}) is True         # explicit opt-out


def test_lag_breach_fires_once_per_sustained_excursion():
    now = [0.0]
    hits = []
    coord = ReplicationCoordinator(
        ReplicationConfig("async", lag_records=10, lag_breach_s=1.0),
        on_lag_breach=hits.append, clock=lambda: now[0])
    coord.note_local({"S": 100})                  # 100 behind, 0 acked
    coord.on_ack({"S": 2})                        # starts the excursion
    assert hits == []                             # not sustained yet
    now[0] = 2.0
    coord.on_ack({"S": 3})
    assert len(hits) == 1 and "lag" in hits[0]
    now[0] = 3.0
    coord.on_ack({"S": 4})                        # still breached: once
    assert len(hits) == 1
    coord.on_ack({"S": 100})                      # recovered: re-arms
    now[0] = 10.0
    coord.note_local({"S": 300})
    coord.on_ack({"S": 101})
    now[0] = 20.0
    coord.on_ack({"S": 102})
    assert len(hits) == 2


def test_metrics_shape():
    coord = ReplicationCoordinator(ReplicationConfig("async"))
    m = coord.metrics()
    for k in ("mode", "role", "standbys", "lag_records", "lag_seconds",
              "shipped_records", "acks", "rejected_generation",
              "barrier_timeouts"):
        assert k in m
    sb = ReplicationCoordinator(
        ReplicationConfig("async", role="standby", peer="h:1"))
    sb.note_applied("S", 3, 100)
    sb.note_generation(2)
    m = sb.metrics()
    assert m["applied_watermark"] == {"S": 3}
    assert m["source_generation"] == 2


# ---------------------------------------------------------------------------
# in-process failover: standby converges, promote replays, fencing bites
# ---------------------------------------------------------------------------

def _no_resolve(app, stream):
    raise KeyError(stream)


def _mk_primary(tmp_path, mode_ann=""):
    mgr = SiddhiManager()
    mgr.set_persistence_store(
        FileSystemPersistenceStore(str(tmp_path / "pstore")))
    rt = mgr.create_app_runtime(
        HOST + "@app:name('HA')\n"
        + f"@app:durability('batch', dir='{tmp_path / 'pwal'}', "
          f"segment.bytes='2048')\n" + mode_ann + BODY)
    rows = []
    rt.add_batch_callback("Out", lambda b: rows.extend(
        map(tuple, b.rows(rt.strings))))
    rt.start()
    srv = NetServer(_no_resolve, port=0,
                    repl_resolve=lambda app: {"HA": rt}[app]).start()
    return mgr, rt, srv, rows


def _mk_standby(tmp_path, port, extra=""):
    mgr = SiddhiManager()
    mgr.set_persistence_store(
        FileSystemPersistenceStore(str(tmp_path / "sstore")))
    rt = mgr.create_app_runtime(
        HOST + "@app:name('HA')\n"
        + f"@app:durability('batch', dir='{tmp_path / 'swal'}', "
          f"segment.bytes='2048')\n"
        + f"@app:replication('async', role='standby', "
          f"peer='127.0.0.1:{port}'{extra})\n" + BODY)
    rows = []
    rt.add_batch_callback("Out", lambda b: rows.extend(
        map(tuple, b.rows(rt.strings))))
    rt.start()
    return mgr, rt, rows


def test_failover_end_to_end(tmp_path):
    mgr_p, rt_p, srv, rows_p = _mk_primary(tmp_path)
    mgr_s, rt_s, rows_s = _mk_standby(tmp_path, srv.port)
    try:
        assert rt_s.is_standby()
        with pytest.raises(RuntimeError, match="standby"):
            feed(rt_s, frames(1))               # ingest is BLOCKED
        frs = frames()
        feed(rt_p, frs)
        wm_p = rt_p.wal.watermark()
        assert wm_p.get("S", 0) == len(frs)
        # standby's log converges to the primary's watermark
        assert wait_for(lambda: rt_s.replication.applied_watermark()
                        == wm_p)
        # acks flowed back: the primary sees the standby's progress
        assert wait_for(lambda: rt_p.replication is not None
                        and rt_p.replication.metrics()
                        .get("acked_watermark") == wm_p)
        assert rt_p.replication.standbys() == 1
        # happy path: ZERO error-store captures on either side
        assert len(rt_s.error_store) == 0
        assert len(rt_p.error_store) == 0
        # --- machine loss: the primary goes away -------------------------
        srv.stop()
        mgr_p.shutdown()
        report = rt_s.promote()
        assert report["promoted"] and report["generation"] >= 1
        assert report["recovery"]["replayed_frames"] == len(frs)
        # byte-identical replay: the standby computed the SAME outputs
        assert sorted(rows_s) == sorted(rows_p) and rows_p
        # the log itself is byte-identical up to the failover point
        pb = b"".join(open(f, "rb").read() for f in
                      sorted(glob.glob(str(tmp_path / "pwal"
                                           / "wal-*.seg"))))
        sb = b"".join(open(f, "rb").read() for f in
                      sorted(glob.glob(str(tmp_path / "swal"
                                           / "wal-*.seg"))))
        assert pb == sb and pb
        # promoted: ingest unblocked, seqs continue past the watermark
        feed(rt_s, frames(1, seed=11))
        assert rt_s.wal.watermark()["S"] == len(frs) + 1
        # observability: role flip + promotion report are surfaced
        stats = rt_s.statistics()["replication"]
        assert stats["role"] == "primary" and stats["promoted"]
        dur = rt_s.explain()["durability"]
        assert dur["promotion"]["generation"] == report["generation"]
        assert dur["recovery"]["replayed_frames"] == len(frs)
    finally:
        srv.stop()
        mgr_p.shutdown()
        mgr_s.shutdown()


def test_catchup_over_truncated_wal_ships_snapshot(tmp_path):
    """A standby subscribing from scratch AFTER a snapshot barrier
    truncated the primary's log gets the Revision chain, then the
    record stream — a gap is a catch-up, not an error."""
    mgr_p, rt_p, srv, _rows = _mk_primary(tmp_path)
    try:
        feed(rt_p, frames(6, batch=32))
        rt_p.persist()                      # barrier + truncate sealed
        assert rt_p.wal.truncated_segments > 0
        wm_p = rt_p.wal.watermark()
        mgr_s, rt_s, _ = _mk_standby(tmp_path, srv.port)
        try:
            assert wait_for(lambda: rt_s.replication.applied_watermark()
                            .get("S", 0) >= wm_p["S"])
            m = rt_s.statistics()["replication"]
            assert m["applied_snapshots"] >= 1      # the chain shipped
            assert len(rt_s.error_store) == 0       # and NOT as an error
            # the shipped revision restores at promote
            srv.stop(), mgr_p.shutdown()
            report = rt_s.promote()
            assert report["recovery"]["restored_revision"] is not None
        finally:
            mgr_s.shutdown()
    finally:
        srv.stop()
        mgr_p.shutdown()


def test_deposed_primary_is_rejected_loudly(tmp_path):
    """Split-brain: after the standby fences, frames stamped with the
    old generation are refused — error-store capture + counter, no
    silent apply."""
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        HOST + "@app:name('HA')\n"
        + f"@app:durability('batch', dir='{tmp_path / 'wal'}')\n"
        + "@app:replication('async', role='standby', "
          "peer='127.0.0.1:1')\n" + BODY)
    coord = rt._ensure_replication()
    rt._standby_active = True
    rt._started = True
    rt.wal = None
    rt._open_wal()
    recv = WalReceiver(rt, coord, "127.0.0.1:1")    # never started
    coord.note_generation(3)                        # saw primary gen 3
    with pytest.raises(ReplProtocolError, match="deposed"):
        recv._check_generation(2)
    assert coord.rejected_generation == 1
    ents = rt.error_store.entries("_replication")
    assert len(ents) == 1 and ents[0].point == "repl.fence"
    mgr.shutdown()


def test_shipper_refuses_subscriber_from_the_future(tmp_path):
    """The OTHER split-brain direction: a primary asked to serve a
    standby that has seen a NEWER generation knows it was deposed."""
    from siddhi_tpu.net.repl import WalShipper
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        HOST + "@app:name('HA')\n"
        + f"@app:durability('batch', dir='{tmp_path / 'wal'}')\n" + BODY)
    rt.start()
    coord = rt._ensure_replication(default=True)
    wrote = []
    sh = WalShipper(rt, coord, wrote.append,
                    {"app": "HA", "watermark": {}, "generation": 99},
                    stop=lambda: False)
    with pytest.raises(ReplProtocolError, match="deposed"):
        sh._ship()
    assert coord.rejected_generation == 1
    mgr.shutdown()


# ---------------------------------------------------------------------------
# the semi-sync barrier over the wire
# ---------------------------------------------------------------------------

SRC = "@source(type='tcp', port='0')\n"


def _wire_app(tmp_path, repl):
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        HOST + "@app:name('SemiSync')\n"
        + f"@app:durability('batch', dir='{tmp_path / 'wal'}')\n"
        + repl + SRC + BODY)
    rt.start()
    cols = TcpFrameClient.cols_of_schema(rt.schemas["S"])
    cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "S", cols)
    return mgr, rt, cli


def test_semi_sync_barrier_fails_without_standby(tmp_path):
    """No standby connected -> the durable-ACK barrier must FAIL (the
    producer retransmits) rather than lie about durability."""
    mgr, rt, cli = _wire_app(
        tmp_path, "@app:replication('semi-sync', ack.timeout='200 ms')\n")
    try:
        cols, ts = frames(1)[0]
        cli.send_batch(cols, ts)
        # the server fails the barrier with FrameDesync and drops the
        # link: the client sees either its own timeout error or the
        # hangup — both force the retransmit path
        with pytest.raises((NetClientError, EOFError, OSError)):
            cli.barrier(timeout=3.0)
        assert rt.replication.barrier_timeouts >= 1
    finally:
        cli.close()
        mgr.shutdown()


def test_semi_sync_degrade_async_waives_the_wait(tmp_path):
    mgr, rt, cli = _wire_app(
        tmp_path, "@app:replication('semi-sync', ack.timeout='200 ms', "
        "degrade='async')\n")
    try:
        cols, ts = frames(1)[0]
        cli.send_batch(cols, ts)
        cli.barrier(timeout=5.0)            # succeeds: explicit opt-out
        assert rt.replication.barrier_timeouts == 0
    finally:
        cli.close()
        mgr.shutdown()


def test_semi_sync_barrier_succeeds_with_live_standby(tmp_path):
    """The full semi-sync contract: PING/ACK completes only once the
    standby appended — and it does, because the receiver acks every
    heartbeat immediately."""
    mgr_p, rt_p, srv, _rows = _mk_primary(
        tmp_path, "@app:replication('semi-sync', ack.timeout='5 sec', "
        "heartbeat='50 ms')\n")
    cols = None
    mgr_s = None
    try:
        mgr_s, rt_s, _ = _mk_standby(tmp_path, srv.port)
        assert wait_for(lambda: rt_p.replication is not None
                        and rt_p.replication.standbys() == 1)
        feed(rt_p, frames(2))
        ok = rt_p.replication.wait_ack(rt_p.wal.watermark(),
                                       timeout_s=10.0)
        assert ok is True
        assert rt_p.replication.barrier_waits >= 1
    finally:
        srv.stop()
        mgr_p.shutdown()
        if mgr_s is not None:
            mgr_s.shutdown()


# ---------------------------------------------------------------------------
# plan-time guards + observability surfacing (satellite pins)
# ---------------------------------------------------------------------------

def test_replication_requires_durability():
    from siddhi_tpu.core.planner import PlanError
    mgr = SiddhiManager()
    with pytest.raises(PlanError, match="SA14"):
        mgr.create_app_runtime(
            HOST + "@app:name('X')\n@app:replication('async')\n" + BODY)
    mgr.shutdown()


def test_recovery_report_surfaces_in_snapshot_info_and_explain(tmp_path):
    """Satellite bugfix pin: the last recover() report must show in
    BOTH the snapshot endpoint payload and explain()['durability']."""
    from siddhi_tpu.core.wal import WriteAheadLog as _W
    mgr = SiddhiManager()
    mgr.set_persistence_store(FileSystemPersistenceStore(str(tmp_path)))
    app = (HOST + "@app:name('Rec')\n"
           + f"@app:durability('batch', dir='{tmp_path / 'wal'}')\n"
           + BODY)
    rt = mgr.create_app_runtime(app)
    rt.start()
    feed(rt, frames(2))
    rt.wal.close()
    mgr._runtimes.clear()                   # simulated crash
    rt2 = mgr.create_app_runtime(app)
    rt2.start()                             # recover() runs on start
    dur = rt2.explain()["durability"]
    assert dur["recovery"]["replayed_frames"] == 2
    from siddhi_tpu.service import SiddhiService
    svc = SiddhiService.__new__(SiddhiService)
    svc.runtimes = {"Rec": rt2}
    info = svc.snapshot_info("Rec")
    assert info["recovery"]["replayed_frames"] == 2
    mgr.shutdown()
