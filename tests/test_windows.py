"""Window + aggregation integration tests (sequential backend).

Mirrors reference test expectations (reference: modules/siddhi-core/src/test/
.../query/window/{Length,LengthBatch,Time,TimeBatch,ExternalTime}WindowTestCase.java,
aggregator tests under query/aggregator/)."""
import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def collect(rt, stream):
    got = []
    rt.add_callback(stream, lambda evs: got.extend(evs))
    return got


def test_length_window_avg(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (symbol string, price double);
        from S#window.length(3) select symbol, avg(price) as ap insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    for p in [10.0, 20.0, 30.0, 40.0]:
        h.send(("A", p))
        rt.flush()
    # window slides: avg(10)=10, avg(10,20)=15, avg(10,20,30)=20, avg(20,30,40)=30
    assert [e.data for e in got] == [("A", 10.0), ("A", 15.0), ("A", 20.0), ("A", 30.0)]


def test_length_window_sum_expired_order(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (v int);
        from S#window.length(2) select sum(v) as s insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    for v in [1, 2, 3, 4]:
        h.send((v,))
    rt.flush()
    # sums: 1, 3, (expire 1) 5, (expire 2) 7
    assert [e.data for e in got] == [(1,), (3,), (5,), (7,)]


def test_length_batch_no_output_until_full(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (symbol string, price float, volume int);
        from S#window.lengthBatch(4) select symbol, price, volume insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    h.send(("IBM", 700.0, 0))
    h.send(("WSO2", 60.5, 1))
    rt.flush()
    assert got == []   # reference lengthBatchWindowTest1
    for i in range(2, 6):
        h.send(("X", 1.0, i))
    rt.flush()
    # first full batch of 4 emitted; events 5,6 pending
    assert [e.data[2] for e in got] == [0, 1, 2, 3]


def test_length_batch_sum(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (v int);
        from S#window.lengthBatch(3) select sum(v) as s insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    for v in [1, 2, 3, 10, 20, 30]:
        h.send((v,))
    rt.flush()
    # batch1: running sums 1,3,6; batch2 (after expired+reset): 10,30,60
    assert [e.data for e in got] == [(1,), (3,), (6,), (10,), (30,), (60,)]


def test_group_by_avg(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (symbol string, price double);
        from S#window.length(4) select symbol, avg(price) as ap
        group by symbol insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    h.send(("A", 10.0))
    h.send(("B", 100.0))
    h.send(("A", 20.0))
    h.send(("B", 200.0))
    rt.flush()
    assert [e.data for e in got] == [("A", 10.0), ("B", 100.0),
                                     ("A", 15.0), ("B", 150.0)]


def test_time_window_virtual_clock(mgr):
    rt = mgr.create_app_runtime("""
        @app:playback
        define stream S (v int);
        from S#window.time(1 sec) select sum(v) as s insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    h.send((10,), timestamp=1000)
    h.send((20,), timestamp=1500)
    rt.flush()
    assert [e.data for e in got] == [(10,), (30,)]
    # at t=2100 the first event (ts 1000) expired
    rt.set_time(2100)
    h.send((5,), timestamp=2100)
    rt.flush()
    assert got[-1].data == (25,)   # 20 + 5 (10 expired)


def test_time_batch_window(mgr):
    rt = mgr.create_app_runtime("""
        @app:playback
        define stream S (v int);
        from S#window.timeBatch(1 sec) select sum(v) as s insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    h.send((1,), timestamp=1000)
    h.send((2,), timestamp=1400)
    rt.flush()
    assert got == []               # batch not closed yet
    rt.set_time(2000)              # boundary at start+1000 == 2000
    assert [e.data for e in got] == [(1,), (3,)]


def test_external_time_window(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (ts long, v int);
        from S#window.externalTime(ts, 1 sec) select sum(v) as s insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    h.send((1000, 10))
    h.send((1500, 20))
    h.send((2100, 5))   # ts=1000 event expires (1000+1000 <= 2100)
    rt.flush()
    assert [e.data for e in got] == [(10,), (30,), (25,)]


def test_min_max_with_expiry(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (v double);
        from S#window.length(2) select min(v) as lo, max(v) as hi insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    for v in [5.0, 3.0, 9.0, 1.0]:
        h.send((v,))
    rt.flush()
    assert [e.data for e in got] == [(5.0, 5.0), (3.0, 5.0), (3.0, 9.0), (1.0, 9.0)]


def test_stddev_distinct_count(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (sym string, v double);
        from S#window.length(4)
        select stdDev(v) as sd, distinctCount(sym) as dc insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    h.send(("A", 2.0))
    h.send(("B", 4.0))
    h.send(("A", 6.0))
    rt.flush()
    assert got[-1].data[1] == 2            # distinct A,B
    assert got[-1].data[0] == pytest.approx(1.632993161855452)


def test_having_on_aggregate(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (symbol string, price double);
        from S#window.lengthBatch(2) select symbol, avg(price) as ap
        group by symbol having ap > 50 insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    h.send(("A", 10.0))
    h.send(("A", 200.0))
    rt.flush()
    # running per-event: avg=10 (filtered), avg=105 (passes)
    assert [e.data for e in got] == [("A", 105.0)]


def test_output_rate_every_n_events(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (v int);
        from S select v output last every 3 events insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    for v in range(1, 8):
        h.send((v,))
    rt.flush()
    assert [e.data for e in got] == [(3,), (6,)]


def test_output_rate_first(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (v int);
        from S select v output first every 3 events insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    for v in range(1, 8):
        h.send((v,))
    rt.flush()
    assert [e.data for e in got] == [(1,), (4,), (7,)]


def test_insert_expired_events(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (v int);
        from S#window.length(2) select v insert expired events into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    for v in [1, 2, 3, 4]:
        h.send((v,))
    rt.flush()
    assert [e.data for e in got] == [(1,), (2,)]


def test_insert_all_events(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (v int);
        from S#window.length(2) select v insert all events into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    for v in [1, 2, 3]:
        h.send((v,))
    rt.flush()
    datas = [e.data for e in got]
    assert (1,) in datas and (3,) in datas
    assert len(datas) == 4     # 3 current + 1 expired


def test_sort_window(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (v int);
        from S#window.sort(2, v) select sum(v) as s insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    for v in [5, 1, 9]:
        h.send((v,))
    rt.flush()
    # reference SortWindowProcessor appends the evicted event AFTER the
    # current one, so the current row for 9 still includes it: 5, 6, 15
    assert [e.data for e in got] == [(5,), (6,), (15,)]


def test_delay_window(mgr):
    rt = mgr.create_app_runtime("""
        @app:playback
        define stream S (v int);
        from S#window.delay(1 sec) select v insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    h.send((1,), timestamp=1000)
    rt.flush()
    assert got == []
    rt.set_time(2000)
    assert [e.data for e in got] == [(1,)]


def test_session_window(mgr):
    rt = mgr.create_app_runtime("""
        @app:playback
        define stream S (user string, v int);
        from S#window.session(1 sec, user) select user, sum(v) as s
        group by user insert expired events into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    h.send(("u1", 1), timestamp=1000)
    h.send(("u1", 2), timestamp=1500)
    rt.flush()
    rt.set_time(2600)    # session closes at 1500+1000=2500
    # expired rows carry the post-removal aggregate: remove(1)->2, remove(2)->0
    assert [e.data for e in got] == [("u1", 2), ("u1", 0)]


def test_unbounded_group_by_count(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (sym string);
        from S select sym, count() as c group by sym insert into O;
    """)
    got = collect(rt, "O")
    h = rt.input_handler("S")
    for s in ["A", "B", "A", "A"]:
        h.send((s,))
    rt.flush()
    assert [e.data for e in got] == [("A", 1), ("B", 1), ("A", 2), ("A", 3)]
