"""Device NFA pattern-algebra differential tests: count quantifiers,
logical and/or, absent (not..for), and their interactions — the batched
kernel must reproduce the sequential host matcher's match sets exactly
(the host is pinned against reference semantics in test_patterns.py).
"""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

DEV = "@app:devicePatterns('always')\n"
SEQ = "@app:devicePatterns('never')\n"


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def run_app(mgr, app, sends, out_stream="O", set_time=None):
    rt = mgr.create_app_runtime(app)
    out = []
    rt.add_callback(out_stream, lambda evs: out.extend(e.data for e in evs))
    handlers = {}
    rt.start()
    for sid, row, ts in sends:
        h = handlers.get(sid) or handlers.setdefault(sid, rt.input_handler(sid))
        h.send(row, timestamp=ts)
    rt.flush()
    if set_time is not None:
        rt.set_time(set_time)
    return out, rt


def both(mgr, body, sends, out_stream="O", set_time=None):
    dev, drt = run_app(mgr, DEV + body, sends, out_stream, set_time)
    host, _ = run_app(mgr, SEQ + body, sends, out_stream, set_time)
    from siddhi_tpu.core.pattern_plan import DevicePatternPlan
    assert any(isinstance(p, DevicePatternPlan) for p in drt._plans), \
        "expected the device plan to engage"
    return dev, host


COUNT_BODY = """
define stream T (temp double);
@info(name='q') from e1=T[temp > 30]<2:3> -> e2=T[temp < 10]
select e1[0].temp as t0, e1[1].temp as t1, e2.temp as tl insert into O;
"""


def test_count_basic(mgr):
    sends = [("T", (31.0,), 1000), ("T", (32.0,), 1001), ("T", (5.0,), 1002)]
    dev, host = both(mgr, COUNT_BODY, sends)
    assert dev == host == [(31.0, 32.0, 5.0)]


def test_count_max_and_survivor(mgr):
    # 3 collects (max), then two closing events: the pending count match
    # keeps emitting (host semantics: count-final pms survive)
    sends = [("T", (31.0,), 1000), ("T", (32.0,), 1001), ("T", (33.0,), 1002),
             ("T", (5.0,), 1003), ("T", (4.0,), 1004)]
    dev, host = both(mgr, COUNT_BODY, sends)
    assert dev == host


def test_count_plus_sequence_every(mgr):
    body = """
    define stream S (v int);
    @info(name='q') from every e1=S[v > 0]+, e2=S[v == 0]
    select e1[0].v as first, e1[last].v as last_, e2.v as z insert into O;
    """
    sends = [("S", (1,), 1000), ("S", (2,), 1001), ("S", (0,), 1002),
             ("S", (3,), 1003), ("S", (0,), 1004), ("S", (0,), 1005)]
    dev, host = both(mgr, body, sends)
    assert sorted(dev) == sorted(host)


def test_logical_and(mgr):
    body = """
    define stream A (x int);
    define stream B (y int);
    define stream C (z int);
    @info(name='q') from e1=A and e2=B -> e3=C
    select e1.x as x, e2.y as y, e3.z as z insert into O;
    """
    sends = [("B", (2,), 1000), ("C", (9,), 1001), ("A", (1,), 1002),
             ("C", (3,), 1003)]
    dev, host = both(mgr, body, sends)
    assert dev == host == [(1, 2, 3)]


def test_logical_or_null_side(mgr):
    body = """
    define stream A (x int);
    define stream B (y int);
    @info(name='q') from e1=A or e2=B select e1.x as x, e2.y as y insert into O;
    """
    sends = [("B", (42,), 1000)]
    dev, host = both(mgr, body, sends)
    assert dev == host == [(None, 42)]


def test_logical_and_head_every(mgr):
    body = """
    define stream A (x int);
    define stream B (y int);
    @info(name='q') from every e1=A and e2=B
    select e1.x as x, e2.y as y insert into O;
    """
    sends = [("A", (1,), 1000), ("B", (2,), 1001), ("A", (3,), 1002),
             ("B", (4,), 1003)]
    dev, host = both(mgr, body, sends)
    assert sorted(dev) == sorted(host)


ABSENT_BODY = """
@app:playback
define stream A (x int);
define stream B (y int);
@info(name='q') from e1=A -> not B for 1 sec
select e1.x as x insert into O;
"""


def test_absent_fires_on_deadline(mgr):
    sends = [("A", (7,), 1000)]
    dev, host = both(mgr, ABSENT_BODY, sends, set_time=2100)
    assert dev == host == [(7,)]


def test_absent_suppressed(mgr):
    sends = [("A", (7,), 1000), ("B", (1,), 1500)]
    dev, host = both(mgr, ABSENT_BODY, sends, set_time=3000)
    assert dev == host == []


def test_absent_and_present(mgr):
    body = """
    define stream R (t double);
    define stream T (t double);
    define stream H (h double);
    @info(name='q') from e1=R -> not T[t > e1.t] and e2=H
    select e1.t as rt_, e2.h as h insert into O;
    """
    sends = [("R", (20.0,), 1000), ("H", (55.0,), 1001),
             ("R", (30.0,), 1002), ("T", (35.0,), 1003), ("H", (60.0,), 1004)]
    dev, host = both(mgr, body, sends)
    assert dev == host == [(20.0, 55.0)]


def test_absent_mid_chain(mgr):
    body = """
    @app:playback
    define stream A (x int);
    define stream B (y int);
    define stream C (z int);
    @info(name='q') from e1=A -> not B for 500 milliseconds -> e3=C
    select e1.x as x, e3.z as z insert into O;
    """
    # deadline passes quietly -> C completes
    sends = [("A", (1,), 1000), ("C", (9,), 1700)]
    dev, host = both(mgr, body, sends)
    assert dev == host
    # B arrives inside the window -> killed
    sends2 = [("A", (1,), 1000), ("B", (5,), 1200), ("C", (9,), 1700)]
    dev2, host2 = both(mgr, body, sends2)
    assert dev2 == host2


@pytest.mark.slow
def test_differential_random_algebra(mgr):
    """Fuzz the new shapes against the host oracle."""
    rng = np.random.default_rng(11)
    count_body = """
    define stream S (p double);
    @info(name='q') from every e1=S[p > 100]<2:4> -> e2=S[p < 95]
    select e1[0].p as p0, e1[last].p as pl, e2.p as px insert into O;
    """
    and_body = """
    define stream A (x double);
    define stream B (y double);
    @info(name='q') from every e1=A[x > 50] and e2=B[y > 50] -> e3=A[x > e1.x]
    select e1.x as x, e2.y as y, e3.x as z insert into O;
    """
    for name, body, streams in (("count", count_body, ("S",)),
                                ("and", and_body, ("A", "B"))):
        for trial in range(3):
            n = 40
            ps = np.round(rng.uniform(40, 110, size=n) * 4) / 4
            ts = 1000 + np.cumsum(rng.integers(1, 30, size=n))
            sids = [streams[int(i)] for i in rng.integers(0, len(streams), n)]
            sends = [(sid, (float(p),), int(t))
                     for sid, p, t in zip(sids, ps, ts)]
            dev, host = both(mgr, body, sends)
            assert dev == host, f"{name} trial {trial}: {dev} != {host}"


def test_partitioned_count_device(mgr):
    body = """
    @app:partitionCapacity(8)
    define stream S (sym string, p double);
    partition with (sym of S)
    begin
      @info(name='q') from every e1=S[p > 100]<2:3> -> e2=S[p < 95]
      select e1[0].p as p0, e1[last].p as pl, e2.p as px insert into O;
    end;
    """
    rng = np.random.default_rng(3)
    syms = ["K%d" % i for i in range(5)]
    sends = []
    for i in range(150):
        sends.append((
            "S", (syms[int(rng.integers(5))],
                  float(np.round(rng.uniform(85, 115) * 4) / 4)), 1000 + i))
    dev, drt = run_app(mgr, body, sends)
    host, _ = run_app(mgr, "@app:devicePatterns('never')\n" + body, sends)
    assert sorted(dev) == sorted(host)


def test_count_indexed_capture_unfilled_null(mgr):
    """An indexed capture never filled in THIS match must emit NULL
    (host semantics) — not a zero or a stale value leaked from the
    slot's previous life (round-3 advisor finding)."""
    body = """
    define stream T (temp double);
    @info(name='q') from every e1=T[temp > 30]<1:3> -> e2=T[temp < 10]
    select e1[0].temp as a, e1[1].temp as b, e2.temp as c insert into O;
    """
    sends = [("T", (32.0,), 1000), ("T", (5.0,), 1001),
             ("T", (41.0,), 1002), ("T", (4.0,), 1003)]
    dev, host = both(mgr, body, sends)
    assert dev == host
    assert (32.0, None, 5.0) in host and (41.0, None, 4.0) in host


def test_count_indexed_capture_filled_then_unfilled(mgr):
    # first life fills e1[1]; the reused slot's second life must not leak it
    body = """
    define stream T (temp double);
    @info(name='q') from every e1=T[temp > 30]<1:3> -> e2=T[temp < 10]
    select e1[0].temp as a, e1[1].temp as b, e2.temp as c insert into O;
    """
    sends = [("T", (32.0,), 1000), ("T", (33.0,), 1001), ("T", (5.0,), 1002),
             ("T", (41.0,), 1003), ("T", (4.0,), 1004)]
    dev, host = both(mgr, body, sends)
    assert sorted(dev, key=str) == sorted(host, key=str)


def test_absent_deadline_survives_snapshot_restore(mgr):
    """A pending `not B for T` deadline armed before a snapshot must still
    fire after restore into a fresh runtime (round-3 advisor finding)."""
    body = ABSENT_BODY
    for variant in ("dev", "host"):
        prefix = DEV if variant == "dev" else SEQ
        rt = mgr.create_app_runtime(prefix + body)
        out = []
        rt.add_callback("O", lambda evs: out.extend(e.data for e in evs))
        rt.start()
        rt.input_handler("A").send((7,), timestamp=1000)
        rt.flush()
        snap = rt.snapshot()

        rt2 = mgr.create_app_runtime(prefix + body)
        out2 = []
        rt2.add_callback("O", lambda evs: out2.extend(e.data for e in evs))
        rt2.start()
        rt2.restore(snap)
        rt2.set_time(2100)            # past the 1 sec deadline
        assert out2 == [(7,)], f"{variant}: restored deadline did not fire"


def test_indexed_capture_last_n_falls_back(mgr):
    # 'last-2' is outside the device capture algebra: must fall back to
    # the host matcher, not crash at plan-build time
    body = """
    define stream T (temp double);
    @info(name='q') from e1=T[temp > 30]<1:3> -> e2=T[temp < 10]
    select e1[last-2].temp as a, e2.temp as c insert into O;
    """
    sends = [("T", (32.0,), 1000), ("T", (33.0,), 1001), ("T", (34.0,), 1002),
             ("T", (5.0,), 1003)]
    dev, _ = run_app(mgr, "@app:devicePatterns('auto')\n" + body, sends)
    host, _ = run_app(mgr, SEQ + body, sends)
    assert dev == host


def test_derived_null_selector_falls_back(mgr):
    # `e1[1].temp is null` must EVALUATE the null (host semantics), which
    # the device cannot represent -> host fallback, identical output
    body = """
    define stream T (temp double);
    @info(name='q') from every e1=T[temp > 30]<1:3> -> e2=T[temp < 10]
    select e1[1].temp is null as b, e2.temp as c insert into O;
    """
    sends = [("T", (32.0,), 1000), ("T", (33.0,), 1001), ("T", (5.0,), 1002),
             ("T", (41.0,), 1003), ("T", (4.0,), 1004)]
    dev, _ = run_app(mgr, "@app:devicePatterns('auto')\n" + body, sends)
    host, _ = run_app(mgr, SEQ + body, sends)
    assert dev == host


# ---------------------------------------------------------------------------
# round-4 algebra extensions: every-below-head (slot forking), optional
# states (min-count 0 epsilon cascade), adjacent/multiple counts,
# sequences with logical states (reference: StateInputStreamParser.java:
# 77-143 composes these freely; VERDICT r3 missing #1)
# ---------------------------------------------------------------------------

R4_QUERIES = {
    "every_below": (
        "from e1=S[p > 120] -> every e2=S[p > e1.p] within 1 sec "
        "select e1.p as a, e2.p as b insert into O;"),
    "every_below_3state": (
        "from e1=S[p > 124] -> every e2=S[p > e1.p] -> e3=S[p < 95] "
        "within 1 sec select e1.p as a, e2.p as b, e3.p as c insert into O;"),
    "every_head_and_below": (
        "from every e1=S[p > 124] -> every e2=S[p > e1.p] "
        "within 500 milliseconds select e1.p as a, e2.p as b insert into O;"),
    "min0_mid": (
        "from every e1=S[p > 120] -> e2=S[p > 125]<0:2> -> e3=S[p < 95] "
        "within 1 sec select e1.p as a, e3.p as c insert into O;"),
    "min0_final": (
        "from every e1=S[p > 124] -> e2=S[p > e1.p]<0:3> within 1 sec "
        "select e1.p as a, e2[last].p as b insert into O;"),
    "adjacent_counts": (
        "from every e1=S[p > 122]<1:2> -> e2=S[p < 96]<1:2> -> "
        "e3=S[p > 128] within 1 sec select e1[0].p as a, e2[0].p as b, "
        "e3.p as c insert into O;"),
    "two_counts_separated": (
        "from every e1=S[p > 124]<1:2> -> e2=S[p < 100] -> "
        "e3=S[p > 126]<1:2> within 1 sec select e1[0].p as a, e2.p as b, "
        "e3[0].p as c insert into O;"),
    "sequence_logical_or": (
        "from every e1=S[p > 118], e2=S[p < 100] or e3=S[p > 127] "
        "within 1 sec select e1.p as a, e2.p as b, e3.p as c insert into O;"),
    "sequence_logical_and": (
        "from every e1=S[p > 126], e2=S[p > 90] and e3=S[p > 95] "
        "within 1 sec select e1.p as a insert into O;"),
}


@pytest.mark.parametrize("name", [
    pytest.param(n, marks=pytest.mark.slow)
    if n in ("two_counts_separated", "every_head_and_below") else n
    for n in R4_QUERIES])
def test_differential_r4_algebra(mgr, name):
    body = ("define stream S (p double);\n@info(name='q') "
            + R4_QUERIES[name])
    rng = np.random.default_rng(hash(name) % 2**31)
    for trial in range(2):
        n = 220
        ps = np.round(rng.uniform(88, 132, size=n) * 4) / 4
        ts = 1_000_000 + np.cumsum(rng.integers(1, 25, size=n))
        sends = [("S", (float(p),), int(t)) for p, t in zip(ps, ts)]
        dev, host = both(mgr, body, sends)
        assert dev == host, (name, trial, len(dev), len(host),
                             sorted(set(dev) - set(host))[:3],
                             sorted(set(host) - set(dev))[:3])
