"""Shared-memory frame ring (siddhi_tpu/net/ring.py): SPSC round trip,
wraparound, full-ring backpressure, occupancy, cross-thread use."""
import threading

import pytest

from siddhi_tpu.net.ring import RingError, ShmRing


@pytest.fixture
def ring():
    r = ShmRing.create(slots=4, slot_size=1024)
    yield r
    r.close()
    r.unlink()


def test_roundtrip_and_attach(ring):
    other = ShmRing.attach(ring.name)
    assert other.slots == 4 and other.capacity == 1024
    other.push(b"hello")
    other.push(b"world")
    assert ring.pop(timeout=1) == b"hello"
    assert ring.pop(timeout=1) == b"world"
    assert ring.pop(timeout=0.01) is None
    other.close()


def test_wraparound(ring):
    for round_ in range(5):               # 20 frames through 4 slots
        for i in range(4):
            assert ring.push(f"m{round_}-{i}".encode(), timeout=1)
        for i in range(4):
            assert ring.pop(timeout=1) == f"m{round_}-{i}".encode()


def test_full_ring_blocks_until_consumed(ring):
    for i in range(4):
        ring.push(b"x")
    assert ring.occupancy() == (4, 4)
    assert not ring.push(b"y", timeout=0.05)      # full: times out

    def consume():
        ring.pop(timeout=2)
    t = threading.Thread(target=consume)
    t.start()
    assert ring.push(b"y", timeout=2)             # slot freed
    t.join()
    assert ring.occupancy() == (4, 4)


def test_oversized_frame_rejected(ring):
    with pytest.raises(RingError, match="slot capacity"):
        ring.push(b"z" * 2048)


def test_join_barrier(ring):
    ring.push(b"a")
    assert not ring.join(timeout=0.05)            # consumer behind
    assert ring.pop(timeout=1) == b"a"
    assert ring.join(timeout=1)


def test_threaded_producer_consumer(ring):
    N = 200
    got = []

    def produce():
        p = ShmRing.attach(ring.name)
        for i in range(N):
            assert p.push(str(i).encode(), timeout=5)
        p.close()

    t = threading.Thread(target=produce)
    t.start()
    while len(got) < N:
        m = ring.pop(timeout=5)
        assert m is not None
        got.append(int(m))
    t.join()
    assert got == list(range(N))


def test_attach_rejects_foreign_segment():
    from multiprocessing import shared_memory
    shm = shared_memory.SharedMemory(create=True, size=4096)
    try:
        with pytest.raises(RingError, match="magic"):
            ShmRing.attach(shm.name)
    finally:
        shm.close()
        shm.unlink()
