"""Device (batched) NFA vs sequential host matcher — differential tests.

The batched kernel must produce exactly the reference-semantics match set
(zero false matches / zero misses) on every supported pattern shape; the
sequential matcher (tests/test_patterns.py pins its semantics against the
reference) is the oracle.
"""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager


DEV = "@app:devicePatterns('always')"
SEQ = "@app:devicePatterns('never')"


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def run_app(mgr, app, sends, out_stream="M"):
    """sends: [(stream_id, row, ts)] — returns list of output data tuples."""
    rt = mgr.create_app_runtime(app)
    out = []
    rt.add_callback(out_stream, lambda evs: out.extend(e.data for e in evs))
    handlers = {}
    rt.start()
    for sid, row, ts in sends:
        h = handlers.get(sid) or handlers.setdefault(sid, rt.input_handler(sid))
        h.send(row, timestamp=ts)
    rt.flush()
    return out, rt


BODY_EVERY = """
define stream S (sym string, p double);
@info(name='q') from every e1=S[p > 100] -> e2=S[p > e1.p]
select e1.p as p1, e2.p as p2 insert into M;
"""


def test_every_pattern_basic(mgr):
    sends = [("S", ("A", p), 1000 + i) for i, p in
             enumerate((101.0, 50.0, 102.0, 103.0))]
    dev, rt = run_app(mgr, DEV + BODY_EVERY, sends)
    host, _ = run_app(mgr, SEQ + BODY_EVERY, sends)
    assert dev == host
    assert (101.0, 102.0) in dev and (102.0, 103.0) in dev
    from siddhi_tpu.core.pattern_plan import DevicePatternPlan
    assert isinstance(rt._plan_by_name["q"], DevicePatternPlan)


def test_non_every_single_match(mgr):
    body = """
    define stream S (p double);
    @info(name='q') from e1=S[p > 100] -> e2=S[p > e1.p]
    select e1.p as p1, e2.p as p2 insert into M;
    """
    sends = [("S", (p,), 1000 + i) for i, p in
             enumerate((101.0, 102.0, 103.0, 104.0))]
    dev, _ = run_app(mgr, DEV + body, sends)
    host, _ = run_app(mgr, SEQ + body, sends)
    assert dev == host == [(101.0, 102.0)]


def test_within_expiry(mgr):
    body = """
    define stream S (p double);
    @info(name='q') from every e1=S[p > 100] -> e2=S[p > e1.p] within 1 sec
    select e1.p as p1, e2.p as p2 insert into M;
    """
    sends = [("S", (101.0,), 1000), ("S", (102.0,), 2500),
             ("S", (150.0,), 2600), ("S", (151.0,), 2700)]
    dev, _ = run_app(mgr, DEV + body, sends)
    host, _ = run_app(mgr, SEQ + body, sends)
    assert dev == host
    assert (101.0, 102.0) not in dev          # expired (1500ms > 1s)
    assert (102.0, 150.0) in dev


def test_sequence_strictness(mgr):
    body = """
    define stream S (p double);
    @info(name='q') from every e1=S[p > 100], e2=S[p > e1.p]
    select e1.p as p1, e2.p as p2 insert into M;
    """
    # 101, 50 (breaks contiguity), 102, 103 -> only (102,103)
    sends = [("S", (p,), 1000 + i) for i, p in
             enumerate((101.0, 50.0, 102.0, 103.0))]
    dev, _ = run_app(mgr, DEV + body, sends)
    host, _ = run_app(mgr, SEQ + body, sends)
    assert dev == host == [(102.0, 103.0)]


def test_two_streams_three_states(mgr):
    body = """
    define stream A (x int);
    define stream B (y int);
    @info(name='q') from every e1=A[x > 0] -> e2=B[y > e1.x] -> e3=A[x > e2.y]
    select e1.x as a, e2.y as b, e3.x as c insert into M;
    """
    sends = [("A", (1,), 1000), ("B", (5,), 1001), ("A", (7,), 1002),
             ("B", (9,), 1003), ("A", (20,), 1004)]
    dev, _ = run_app(mgr, DEV + body, sends)
    host, _ = run_app(mgr, SEQ + body, sends)
    assert dev == host
    assert (1, 5, 7) in dev and (7, 9, 20) in dev


def test_single_state_every(mgr):
    body = """
    define stream S (p double);
    @info(name='q') from every e1=S[p > 100]
    select e1.p as p1 insert into M;
    """
    sends = [("S", (p,), 1000 + i) for i, p in
             enumerate((101.0, 50.0, 150.0))]
    dev, _ = run_app(mgr, DEV + body, sends)
    host, _ = run_app(mgr, SEQ + body, sends)
    assert dev == host == [(101.0,), (150.0,)]


def test_string_predicates(mgr):
    body = """
    define stream S (sym string, p double);
    @info(name='q') from every e1=S[sym == 'IBM'] -> e2=S[sym == e1.sym and p > e1.p]
    select e1.p as p1, e2.p as p2 insert into M;
    """
    sends = [("S", ("IBM", 10.0), 1000), ("S", ("WSO2", 99.0), 1001),
             ("S", ("IBM", 12.0), 1002)]
    dev, _ = run_app(mgr, DEV + body, sends)
    host, _ = run_app(mgr, SEQ + body, sends)
    assert dev == host == [(10.0, 12.0)]


def test_having_and_limit(mgr):
    body = """
    define stream S (p double);
    @info(name='q') from every e1=S[p > 0] -> e2=S[p > e1.p]
    select e1.p as p1, e2.p as p2 having p2 - p1 > 5 insert into M;
    """
    # e1=1.0 consumes e2=2.0 (first match) and retires -> having drops it;
    # e1=2.0 completes with 10.0 and passes having
    sends = [("S", (p,), 1000 + i) for i, p in
             enumerate((1.0, 2.0, 10.0))]
    dev, _ = run_app(mgr, DEV + body, sends)
    host, _ = run_app(mgr, SEQ + body, sends)
    assert dev == host == [(2.0, 10.0)]


def test_snapshot_restore_device(mgr):
    app = DEV + BODY_EVERY
    rt = mgr.create_app_runtime(app)
    h = rt.input_handler("S")
    rt.start()
    h.send(("A", 101.0), timestamp=1000)
    rt.flush()
    snap = rt.snapshot()

    rt2 = mgr.create_app_runtime(app)
    out = []
    rt2.add_callback("M", lambda evs: out.extend(e.data for e in evs))
    rt2.restore(snap)
    rt2.input_handler("S").send(("A", 102.0), timestamp=1001)
    rt2.flush()
    assert out == [(101.0, 102.0)]


@pytest.mark.slow
def test_differential_random(mgr):
    """Fuzz: random event tapes through device and host matchers."""
    rng = np.random.default_rng(7)
    bodies = [
        ("pattern", DEV + BODY_EVERY, SEQ + BODY_EVERY),
        ("sequence",
         DEV + """
         define stream S (sym string, p double);
         @info(name='q') from every e1=S[p > 100], e2=S[p > e1.p]
         select e1.p as p1, e2.p as p2 insert into M;
         """,
         SEQ + """
         define stream S (sym string, p double);
         @info(name='q') from every e1=S[p > 100], e2=S[p > e1.p]
         select e1.p as p1, e2.p as p2 insert into M;
         """),
        ("within",
         DEV + """
         define stream S (sym string, p double);
         @info(name='q') from every e1=S[p > 100] -> e2=S[p > e1.p] within 50 milliseconds
         select e1.p as p1, e2.p as p2 insert into M;
         """,
         SEQ + """
         define stream S (sym string, p double);
         @info(name='q') from every e1=S[p > 100] -> e2=S[p > e1.p] within 50 milliseconds
         select e1.p as p1, e2.p as p2 insert into M;
         """),
    ]
    for name, dev_app, seq_app in bodies:
        for trial in range(3):
            n = 40
            # quarter-steps are exactly representable in f32: the device
            # kernel computes DOUBLE in f32 by default (documented policy,
            # @app:devicePrecision('f64') opts out)
            ps = np.round(rng.uniform(90, 110, size=n) * 4) / 4
            ts = 1000 + np.cumsum(rng.integers(1, 30, size=n))
            sends = [("S", ("A", float(p)), int(t)) for p, t in zip(ps, ts)]
            dev, _ = run_app(mgr, dev_app, sends)
            host, _ = run_app(mgr, seq_app, sends)
            assert dev == host, f"{name} trial {trial}: {dev} != {host}"
