"""Public columnar ingest (InputHandler.send_batch) — the struct-of-arrays
user API the benchmark drives (VERDICT r4 weak #6: measure the public
junction path, not runtime privates)."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

HEAD = "define stream S (sym string, p double, v int);\n"


def _mk(app):
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(e.data for e in evs))
    rt.start()
    return m, rt, rows


def test_send_batch_filter_and_string_encode():
    m, rt, rows = _mk(HEAD + "from S[p > 100] select sym, p insert into Out;")
    h = rt.input_handler("S")
    h.send_batch({"sym": ["A", "B", "C"],
                  "p": np.array([101.0, 99.0, 150.0]),
                  "v": np.array([1, 2, 3])},
                 timestamps=np.array([1000, 1001, 1002]))
    rt.flush()
    assert rows == [("A", 101.0), ("C", 150.0)]
    m.shutdown()


def test_send_batch_precoded_string_codes():
    m, rt, rows = _mk(HEAD + "from S select sym insert into Out;")
    codes = np.array([rt.strings.encode(s) for s in ("X", "Y")], np.int32)
    rt.input_handler("S").send_batch(
        {"sym": codes, "p": np.zeros(2), "v": np.zeros(2, np.int32)})
    rt.flush()
    assert rows == [("X",), ("Y",)]
    m.shutdown()


def test_send_batch_orders_after_buffered_rows():
    m, rt, rows = _mk(HEAD + "from S select v insert into Out;")
    h = rt.input_handler("S")
    h.send(("A", 1.0, 1))          # buffered in the row builder
    h.send_batch({"sym": ["B"], "p": [2.0], "v": [2]})
    rt.flush()
    assert rows == [(1,), (2,)]
    m.shutdown()


def test_send_batch_pattern_sequence_matches_row_path():
    app = HEAD + ("from every e1=S[p > 100] -> e2=S[p > e1.p] within 1 sec "
                  "select e1.p as p1, e2.p as p2 insert into Out;")
    prices = [101.0, 105.0, 50.0, 110.0, 120.0]
    ts = np.arange(1000, 1000 + len(prices) * 10, 10, dtype=np.int64)

    m1, rt1, rows1 = _mk(app)
    for p, t in zip(prices, ts):
        rt1.input_handler("S").send(("A", p, 1), timestamp=int(t))
    rt1.flush()
    m1.shutdown()

    m2, rt2, rows2 = _mk(app)
    rt2.input_handler("S").send_batch(
        {"sym": ["A"] * len(prices), "p": np.array(prices),
         "v": np.ones(len(prices), np.int32)}, timestamps=ts)
    rt2.flush()
    m2.shutdown()
    assert rows1 == rows2 and rows1


def test_send_batch_playback_advances_clock():
    m, rt, rows = _mk("@app:playback\n" + HEAD +
                      "from S select v insert into Out;")
    rt.input_handler("S").send_batch(
        {"sym": ["A"], "p": [1.0], "v": [7]},
        timestamps=np.array([123456], np.int64))
    rt.flush()
    assert rt.now_ms() == 123456
    m.shutdown()


def test_send_batch_async_mode_delivers_on_flush():
    m, rt, rows = _mk("@app:async\n" + HEAD +
                      "from S[p > 100] select v insert into Out;")
    rt.input_handler("S").send_batch(
        {"sym": ["A", "B"], "p": np.array([150.0, 50.0]),
         "v": np.array([1, 2], np.int32)})
    rt.flush()
    assert rows == [(1,)]
    m.shutdown()


def test_send_batch_errors():
    m, rt, _rows = _mk(HEAD + "from S select v insert into Out;")
    h = rt.input_handler("S")
    with pytest.raises(ValueError, match="missing columns"):
        h.send_batch({"sym": ["A"], "p": [1.0]})
    with pytest.raises(ValueError, match="rows"):
        h.send_batch({"sym": ["A"], "p": [1.0, 2.0], "v": [1]})
    with pytest.raises(ValueError, match="timestamps"):
        h.send_batch({"sym": ["A"], "p": [1.0], "v": [1]},
                     timestamps=np.array([1, 2]))
    with pytest.raises(Exception, match="unknown stream"):
        rt.send_columnar("Nope", {}, None)
    m.shutdown()


def test_send_batch_scalar_timestamp_broadcasts():
    m, rt, rows = _mk(HEAD + "from S select v insert into Out;")
    rt.input_handler("S").send_batch(
        {"sym": ["A", "B"], "p": [1.0, 2.0], "v": [1, 2]}, timestamps=1000)
    rt.flush()
    assert rows == [(1,), (2,)]
    m.shutdown()


def test_send_batch_unstamped_does_not_anchor_playback_clock():
    """Wall-stamped batches must not move a @app:playback app's event-time
    clock (review r5): a later historical tape would then run 'backwards'
    against within/absent deadlines."""
    m, rt, _rows = _mk("@app:playback\n" + HEAD +
                       "from S select v insert into Out;")
    rt.input_handler("S").send_batch({"sym": ["A"], "p": [1.0], "v": [1]})
    rt.flush()
    assert rt._clock_ms is None
    m.shutdown()


def test_send_batch_async_fifo_with_queued_batches():
    """Async mode: buffered builder rows staged via send_batch must not
    jump ahead of older batches still in the ingest queue (review r5)."""
    m = SiddhiManager()
    rt = m.create_app_runtime(
        "@app:async(batch.size.max='4')\ndefine stream S (x int);\n"
        "from e1=S[x==1], e2=S[x==2] select e1.x as a, e2.x as b "
        "insert into Out;")
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(e.data for e in evs))
    rt.start()
    h = rt.input_handler("S")
    for _ in range(7):        # one full batch queued + 3 rows buffered
        h.send((0,))
    h.send((1,))              # buffered
    h.send_batch({"x": [2]})  # must stay AFTER the buffered 1
    rt.flush()
    m.shutdown()
    assert rows == [(1, 2)], rows


def test_send_batch_scalar_column_rejected():
    m, rt, _rows = _mk(HEAD + "from S select v insert into Out;")
    with pytest.raises(ValueError, match="1-d"):
        rt.input_handler("S").send_batch({"sym": "AB", "p": 1.0, "v": 1})
    m.shutdown()


# ---------------------------------------------------------------------------
# columnar fast path (zero-copy BatchBuilder segments, PR 3)
# ---------------------------------------------------------------------------

def _capture_batches(app):
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    batches = []
    rt.add_batch_callback("S", batches.append)
    rt.start()
    return m, rt, batches


PASSTHRU = HEAD + "from S select sym insert into Out;"


def test_columnar_batch_byte_identical_to_row_path():
    """The fast path must produce an EventBatch byte-identical (dtypes
    and values, timestamps, seqs, string codes) to the per-row append
    path fed the same data."""
    data = [("A", 101.5, 1), ("B", -2.0, 7), ("A", 0.25, 3)]
    ts = [1000, 1001, 1002]

    m1, rt1, via_rows = _capture_batches(PASSTHRU)
    for (s, p, v), t in zip(data, ts):
        rt1.input_handler("S").send((s, p, v), timestamp=t)
    rt1.flush()
    m1.shutdown()

    m2, rt2, via_cols = _capture_batches(PASSTHRU)
    rt2.input_handler("S").send_batch(
        {"sym": [s for s, _p, _v in data],
         "p": np.array([p for _s, p, _v in data]),
         "v": [v for _s, _p, v in data]},
        timestamps=np.array(ts, np.int64))
    rt2.flush()
    m2.shutdown()

    assert len(via_rows) == len(via_cols) == 1
    br, bc = via_rows[0], via_cols[0]
    assert br.n == bc.n
    np.testing.assert_array_equal(br.timestamps, bc.timestamps)
    assert br.timestamps.dtype == bc.timestamps.dtype
    np.testing.assert_array_equal(br.seqs, bc.seqs)
    for name in ("sym", "p", "v"):
        assert br.columns[name].dtype == bc.columns[name].dtype, name
        np.testing.assert_array_equal(br.columns[name], bc.columns[name])


def test_columnar_dtype_coercion():
    """Python lists / mismatched dtypes coerce to the schema's device
    dtypes (double -> f64 column, int -> i32, long str codes -> i32)."""
    m, rt, batches = _capture_batches(PASSTHRU)
    rt.input_handler("S").send_batch(
        {"sym": np.array([3, 4], np.int64),      # pre-encoded, wide dtype
         "p": [1, 2],                            # ints for a double column
         "v": np.array([7.0, 8.0])},             # floats for an int column
        timestamps=[5, 6])
    rt.flush()
    b = batches[0]
    assert b.columns["sym"].dtype == np.int32
    assert b.columns["p"].dtype == np.float64
    assert b.columns["p"].tolist() == [1.0, 2.0]
    assert b.columns["v"].dtype == np.int32
    assert b.columns["v"].tolist() == [7, 8]
    m.shutdown()


def test_columnar_string_encoding_vectorized_matches_row_path():
    """str columns encode through the vectorized unique-gather path with
    codes identical to per-row encode (same fresh dictionary)."""
    syms = ["B", "A", "B", "C", "A", "B"]
    m1, rt1, _ = _capture_batches(PASSTHRU)
    row_codes = [rt1.strings.encode(s) for s in syms]
    m1.shutdown()

    m2, rt2, batches = _capture_batches(PASSTHRU)
    rt2.input_handler("S").send_batch(
        {"sym": syms, "p": np.zeros(6), "v": np.zeros(6, np.int32)})
    rt2.flush()
    assert batches[0].columns["sym"].tolist() == row_codes
    # decode round-trips
    assert [rt2.strings.decode(c) for c in
            batches[0].columns["sym"].tolist()] == syms
    m2.shutdown()


def test_columnar_merges_buffered_rows_into_one_batch():
    """Rows buffered via send() merge AHEAD of the columnar segment in
    ONE micro-batch (previously a split pair), preserving order/seqs."""
    m, rt, batches = _capture_batches(PASSTHRU)
    h = rt.input_handler("S")
    h.send(("R1", 1.0, 1), timestamp=100)
    h.send(("R2", 2.0, 2), timestamp=101)
    h.send_batch({"sym": ["C1", "C2"], "p": [3.0, 4.0], "v": [3, 4]},
                 timestamps=[102, 103])
    rt.flush()
    assert len(batches) == 1
    b = batches[0]
    assert b.n == 4
    assert b.timestamps.tolist() == [100, 101, 102, 103]
    assert b.seqs.tolist() == [1, 2, 3, 4]
    dec = [rt.strings.decode(c) for c in b.columns["sym"].tolist()]
    assert dec == ["R1", "R2", "C1", "C2"]
    m.shutdown()


def test_columnar_unsorted_timestamps_do_not_rewind_playback_clock():
    """Playback clock advances by the batch MAX timestamp: an unsorted
    array whose last element is old must not rewind event time."""
    m, rt, _ = _capture_batches("@app:playback\n" + PASSTHRU)
    rt.input_handler("S").send_batch(
        {"sym": ["A", "B", "C"], "p": [0.0] * 3, "v": [0] * 3},
        timestamps=np.array([5000, 9000, 6000], np.int64))
    rt.flush()
    assert rt.now_ms() == 9000
    m.shutdown()


def test_columnar_zero_copy_adoption():
    """A pure columnar send adopts the arrays without copying (the
    struct-of-arrays fast path: no per-row python, no concat)."""
    m, rt, batches = _capture_batches(PASSTHRU)
    p = np.array([1.0, 2.0])
    rt.input_handler("S").send_batch(
        {"sym": np.array([1, 2], np.int32), "p": p,
         "v": np.array([1, 2], np.int32)}, timestamps=[10, 11])
    rt.flush()
    assert batches[0].columns["p"] is p
    m.shutdown()
