"""Public columnar ingest (InputHandler.send_batch) — the struct-of-arrays
user API the benchmark drives (VERDICT r4 weak #6: measure the public
junction path, not runtime privates)."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

HEAD = "define stream S (sym string, p double, v int);\n"


def _mk(app):
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(e.data for e in evs))
    rt.start()
    return m, rt, rows


def test_send_batch_filter_and_string_encode():
    m, rt, rows = _mk(HEAD + "from S[p > 100] select sym, p insert into Out;")
    h = rt.input_handler("S")
    h.send_batch({"sym": ["A", "B", "C"],
                  "p": np.array([101.0, 99.0, 150.0]),
                  "v": np.array([1, 2, 3])},
                 timestamps=np.array([1000, 1001, 1002]))
    rt.flush()
    assert rows == [("A", 101.0), ("C", 150.0)]
    m.shutdown()


def test_send_batch_precoded_string_codes():
    m, rt, rows = _mk(HEAD + "from S select sym insert into Out;")
    codes = np.array([rt.strings.encode(s) for s in ("X", "Y")], np.int32)
    rt.input_handler("S").send_batch(
        {"sym": codes, "p": np.zeros(2), "v": np.zeros(2, np.int32)})
    rt.flush()
    assert rows == [("X",), ("Y",)]
    m.shutdown()


def test_send_batch_orders_after_buffered_rows():
    m, rt, rows = _mk(HEAD + "from S select v insert into Out;")
    h = rt.input_handler("S")
    h.send(("A", 1.0, 1))          # buffered in the row builder
    h.send_batch({"sym": ["B"], "p": [2.0], "v": [2]})
    rt.flush()
    assert rows == [(1,), (2,)]
    m.shutdown()


def test_send_batch_pattern_sequence_matches_row_path():
    app = HEAD + ("from every e1=S[p > 100] -> e2=S[p > e1.p] within 1 sec "
                  "select e1.p as p1, e2.p as p2 insert into Out;")
    prices = [101.0, 105.0, 50.0, 110.0, 120.0]
    ts = np.arange(1000, 1000 + len(prices) * 10, 10, dtype=np.int64)

    m1, rt1, rows1 = _mk(app)
    for p, t in zip(prices, ts):
        rt1.input_handler("S").send(("A", p, 1), timestamp=int(t))
    rt1.flush()
    m1.shutdown()

    m2, rt2, rows2 = _mk(app)
    rt2.input_handler("S").send_batch(
        {"sym": ["A"] * len(prices), "p": np.array(prices),
         "v": np.ones(len(prices), np.int32)}, timestamps=ts)
    rt2.flush()
    m2.shutdown()
    assert rows1 == rows2 and rows1


def test_send_batch_playback_advances_clock():
    m, rt, rows = _mk("@app:playback\n" + HEAD +
                      "from S select v insert into Out;")
    rt.input_handler("S").send_batch(
        {"sym": ["A"], "p": [1.0], "v": [7]},
        timestamps=np.array([123456], np.int64))
    rt.flush()
    assert rt.now_ms() == 123456
    m.shutdown()


def test_send_batch_async_mode_delivers_on_flush():
    m, rt, rows = _mk("@app:async\n" + HEAD +
                      "from S[p > 100] select v insert into Out;")
    rt.input_handler("S").send_batch(
        {"sym": ["A", "B"], "p": np.array([150.0, 50.0]),
         "v": np.array([1, 2], np.int32)})
    rt.flush()
    assert rows == [(1,)]
    m.shutdown()


def test_send_batch_errors():
    m, rt, _rows = _mk(HEAD + "from S select v insert into Out;")
    h = rt.input_handler("S")
    with pytest.raises(ValueError, match="missing columns"):
        h.send_batch({"sym": ["A"], "p": [1.0]})
    with pytest.raises(ValueError, match="rows"):
        h.send_batch({"sym": ["A"], "p": [1.0, 2.0], "v": [1]})
    with pytest.raises(ValueError, match="timestamps"):
        h.send_batch({"sym": ["A"], "p": [1.0], "v": [1]},
                     timestamps=np.array([1, 2]))
    with pytest.raises(Exception, match="unknown stream"):
        rt.send_columnar("Nope", {}, None)
    m.shutdown()


def test_send_batch_scalar_timestamp_broadcasts():
    m, rt, rows = _mk(HEAD + "from S select v insert into Out;")
    rt.input_handler("S").send_batch(
        {"sym": ["A", "B"], "p": [1.0, 2.0], "v": [1, 2]}, timestamps=1000)
    rt.flush()
    assert rows == [(1,), (2,)]
    m.shutdown()


def test_send_batch_unstamped_does_not_anchor_playback_clock():
    """Wall-stamped batches must not move a @app:playback app's event-time
    clock (review r5): a later historical tape would then run 'backwards'
    against within/absent deadlines."""
    m, rt, _rows = _mk("@app:playback\n" + HEAD +
                       "from S select v insert into Out;")
    rt.input_handler("S").send_batch({"sym": ["A"], "p": [1.0], "v": [1]})
    rt.flush()
    assert rt._clock_ms is None
    m.shutdown()


def test_send_batch_async_fifo_with_queued_batches():
    """Async mode: buffered builder rows staged via send_batch must not
    jump ahead of older batches still in the ingest queue (review r5)."""
    m = SiddhiManager()
    rt = m.create_app_runtime(
        "@app:async(batch.size.max='4')\ndefine stream S (x int);\n"
        "from e1=S[x==1], e2=S[x==2] select e1.x as a, e2.x as b "
        "insert into Out;")
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(e.data for e in evs))
    rt.start()
    h = rt.input_handler("S")
    for _ in range(7):        # one full batch queued + 3 rows buffered
        h.send((0,))
    h.send((1,))              # buffered
    h.send_batch({"x": [2]})  # must stay AFTER the buffered 1
    rt.flush()
    m.shutdown()
    assert rows == [(1, 2)], rows


def test_send_batch_scalar_column_rejected():
    m, rt, _rows = _mk(HEAD + "from S select v insert into Out;")
    with pytest.raises(ValueError, match="1-d"):
        rt.input_handler("S").send_batch({"sym": "AB", "p": 1.0, "v": 1})
    m.shutdown()
