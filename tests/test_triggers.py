"""Triggers + scheduler (reference: core:trigger/*.java, TriggerTestCase;
wall-clock pump replaces the reference's ScheduledExecutorService)."""
import time

import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def collect(rt, sid):
    out = []
    rt.add_callback(sid, lambda evs: out.extend(e.data for e in evs))
    return out


def test_periodic_trigger_virtual_time(mgr):
    rt = mgr.create_app_runtime("""
        define trigger T at every 1 sec;
        from T select triggered_time insert into O;
    """)
    out = collect(rt, "O")
    rt.set_time(0)           # anchor
    rt.set_time(3500)
    assert [r[0] for r in out] == [1000, 2000, 3000]


def test_trigger_feeds_queries(mgr):
    rt = mgr.create_app_runtime("""
        define trigger T at every 500 milliseconds;
        from T select count() as n insert into O;
    """)
    out = collect(rt, "O")
    rt.set_time(0)
    rt.set_time(1000)
    assert out == [(1,), (2,)]


def test_start_trigger(mgr):
    rt = mgr.create_app_runtime("""
        @app:playback
        define trigger T at 'start';
        from T select triggered_time insert into O;
    """)
    out = collect(rt, "O")
    rt.start()
    assert len(out) == 1


def test_cron_trigger_virtual_time(mgr):
    rt = mgr.create_app_runtime("""
        define trigger T at '*/2 * * * * ?';
        from T select triggered_time insert into O;
    """)
    out = collect(rt, "O")
    rt.set_time(0)
    rt.set_time(10_000)
    # every 2 seconds: 2000, 4000, 6000, 8000, 10000
    assert [r[0] for r in out] == [2000, 4000, 6000, 8000, 10000]


def test_trigger_snapshot_keeps_phase(mgr):
    app = """
        define trigger T at every 1 sec;
        from T select triggered_time insert into O;
    """
    rt = mgr.create_app_runtime(app)
    collect(rt, "O")
    rt.set_time(0)
    rt.set_time(1500)        # fired at 1000; next due 2000
    snap = rt.snapshot()

    m2 = SiddhiManager()
    rt2 = m2.create_app_runtime(app)
    out2 = collect(rt2, "O")
    rt2.restore(snap)
    rt2.set_time(2500)
    assert [r[0] for r in out2] == [2000]
    m2.shutdown()


def test_wall_clock_scheduler_fires_triggers(mgr):
    """Real-time mode: timers fire from the scheduler pump without
    set_time() (VERDICT weak #4)."""
    rt = mgr.create_app_runtime("""
        define trigger T at every 100 milliseconds;
        from T select triggered_time insert into O;
    """)
    out = collect(rt, "O")
    rt.start()
    deadline = time.time() + 2.0
    while len(out) < 2 and time.time() < deadline:
        time.sleep(0.02)
    rt.shutdown()
    assert len(out) >= 2


def test_wall_clock_time_window_expires(mgr):
    """A time window's expired events emit without explicit set_time."""
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        from S#window.time(100 milliseconds)
            select x insert expired events into O;
    """)
    out = collect(rt, "O")
    rt.start()
    rt.input_handler("S").send((7,))
    rt.flush()
    deadline = time.time() + 2.0
    while not out and time.time() < deadline:
        time.sleep(0.02)
    rt.shutdown()
    assert out == [(7,)]
