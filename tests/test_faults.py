"""Fault-tolerance layer (core/faults.py + runtime/io/persistence
surgery): the full @OnError action set, ErrorStore + replay, sink
retry/backoff + circuit breaker, device-dispatch graceful degradation
(batch halving -> interpreter quarantine with byte-identical outputs),
and the seeded fault-injection harness that drives it all."""
import warnings

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.faults import (BackoffPolicy, CircuitBreaker,
                                    ErrorStore, FaultInjector,
                                    InjectedFault, is_resource_error)
from siddhi_tpu.core.io import InMemoryBroker


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()
    InMemoryBroker.reset()


def collect(rt, stream):
    rows = []
    rt.add_callback(stream, lambda evs: rows.extend(e.data for e in evs))
    return rows


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

def test_backoff_schedule_deterministic():
    a = list(BackoffPolicy(max_tries=5, base_delay_s=0.1, seed=42).delays())
    b = list(BackoffPolicy(max_tries=5, base_delay_s=0.1, seed=42).delays())
    assert a == b and len(a) == 4
    # exponential envelope with +/-25% jitter
    for i, d in enumerate(a):
        nominal = 0.1 * 2 ** i
        assert 0.74 * nominal <= d <= 1.26 * nominal
    # deadline bounds the cumulative schedule
    short = list(BackoffPolicy(max_tries=100, base_delay_s=0.1, jitter=0.0,
                               deadline_s=0.35).delays())
    assert sum(short) <= 0.35 and len(short) == 2


def test_backoff_run_retries_then_raises():
    calls = []
    pol = BackoffPolicy(max_tries=3, base_delay_s=0.001, seed=0,
                        sleep=lambda s: calls.append(s))
    tries = []

    def fn():
        tries.append(1)
        raise ValueError("nope")
    with pytest.raises(ValueError):
        pol.run(fn)
    assert len(tries) == 3 and len(calls) == 2


def test_circuit_breaker_transitions():
    t = [0.0]
    br = CircuitBreaker(failure_threshold=2, reset_timeout_s=10.0,
                        clock=lambda: t[0])
    assert br.allow() and br.state == br.CLOSED
    br.on_failure()
    assert br.state == br.CLOSED and br.allow()
    br.on_failure()
    assert br.state == br.OPEN and not br.allow()
    t[0] = 11.0                     # reset timeout elapses -> half-open probe
    assert br.allow() and br.state == br.HALF_OPEN
    br.on_failure()                 # probe fails -> re-open immediately
    assert br.state == br.OPEN
    t[0] = 22.0
    assert br.allow()
    br.on_success()                 # probe succeeds -> close
    assert br.state == br.CLOSED and br.allow()
    assert br.metrics()["circuit_opens"] == 2


def test_error_store_bound_and_eviction():
    es = ErrorStore(capacity=3)
    for i in range(5):
        es.add("S", "dispatch", ValueError(f"e{i}"), i)
    assert len(es) == 3 and es.evicted == 2
    ids = [e.id for e in es.entries()]
    assert ids == [3, 4, 5]         # oldest evicted first
    taken = es.take([4])
    assert len(taken) == 1 and len(es) == 2
    d = es.entries()[0].to_dict()
    assert d["point"] == "dispatch" and "e2" in d["error"]


def test_fault_injector_deterministic_and_targeted():
    a = FaultInjector(seed=9, rates={"dispatch": 0.5})
    b = FaultInjector(seed=9, rates={"dispatch": 0.5})
    seq_a, seq_b = [], []
    for seq, inj in ((seq_a, a), (seq_b, b)):
        for _ in range(50):
            try:
                inj.check("dispatch", "p")
                seq.append(0)
            except InjectedFault:
                seq.append(1)
    assert seq_a == seq_b and 0 < sum(seq_a) < 50
    # burst counts + @detail targeting
    inj = FaultInjector(seed=0, counts={"d2h@planA": 2})
    inj.check("d2h", "planB")       # other plan: untouched
    for _ in range(2):
        with pytest.raises(InjectedFault):
            inj.check("d2h", "planA")
    inj.check("d2h", "planA")       # burst exhausted
    assert inj.stats()["fired"]["d2h@planA"] == 2


def test_resource_classification_word_boundaries():
    assert is_resource_error(RuntimeError("RESOURCE_EXHAUSTED: thing"))
    assert is_resource_error(RuntimeError("Out of memory allocating"))
    assert not is_resource_error(RuntimeError("kaboom on worker"))
    assert is_resource_error(InjectedFault("dispatch", kind="resource"))
    assert not is_resource_error(InjectedFault("d2h", kind="fault"))
    assert FaultInjector.parse("dispatch=3,sink.publish=0.5").counts == \
        {"dispatch": 3}


# ---------------------------------------------------------------------------
# @OnError action set
# ---------------------------------------------------------------------------

WIN_APP = """
@OnError(action='{action}'{extra})
define stream S (sym string, p double);
from S#window.length(4) select sym, sum(p) as s group by sym insert into Out;
"""


def test_onerror_unknown_action_rejected(mgr):
    with pytest.raises(Exception, match="unknown @OnError action"):
        mgr.create_app_runtime(WIN_APP.format(action="explode", extra=""))


def test_onerror_log_drops_and_counts(mgr):
    rt = mgr.create_app_runtime(WIN_APP.format(action="log", extra=""))
    rows = collect(rt, "Out")
    rt.fault_injector = FaultInjector(
        seed=1, counts={"dispatch": 1}, kinds={"dispatch": "fault"})
    h = rt.input_handler("S")
    h.send([("K0", 1.0), ("K1", 2.0)])
    rt.flush()
    h.send([("K0", 3.0)])
    rt.flush()
    assert len(rows) == 1           # first batch dropped, second flowed
    assert rt.statistics()["faults"]["S"]["log"] == 1


def test_onerror_store_captures_and_replays(mgr):
    rt = mgr.create_app_runtime(WIN_APP.format(action="store", extra=""))
    rows = collect(rt, "Out")
    rt.fault_injector = FaultInjector(
        seed=1, counts={"dispatch": 1}, kinds={"dispatch": "fault"})
    h = rt.input_handler("S")
    h.send([("K0", 1.0), ("K1", 2.0)])
    rt.flush()
    assert rows == []
    ents = rt.error_store.entries("S")
    assert len(ents) == 1 and len(ents[0].events) == 2
    assert "injected fault" in ents[0].message
    # injector exhausted -> replay re-ingests the captured events
    res = rt.error_store.replay(rt)
    assert res == {"replayed": 1, "failed": 0, "remaining": 0}
    assert sorted(rows) == [("K0", 1.0), ("K1", 2.0)]


def test_onerror_wait_blocks_then_recovers(mgr):
    rt = mgr.create_app_runtime(
        WIN_APP.format(action="wait", extra=", timeout='2 sec'"))
    rows = collect(rt, "Out")
    rt.fault_injector = FaultInjector(
        seed=1, counts={"dispatch": 2}, kinds={"dispatch": "fault"})
    h = rt.input_handler("S")
    h.send([("K0", 1.0), ("K1", 2.0)])
    rt.flush()
    assert len(rows) == 2           # retried through the transient fault
    assert rt.statistics()["faults"]["S"]["wait"] == 1


def test_onerror_wait_deadline_raises(mgr):
    rt = mgr.create_app_runtime(
        WIN_APP.format(action="wait", extra=", timeout='50 ms'"))
    rt.fault_injector = FaultInjector(
        seed=1, counts={"dispatch": 10_000}, kinds={"dispatch": "fault"})
    h = rt.input_handler("S")
    with pytest.raises(RuntimeError, match="gave up"):
        h.send([("K0", 1.0)])
        rt.flush()


def test_onerror_stream_depth_gt0_routes_origin_batch_once(mgr):
    """@OnError(action='stream') under pipelined dispatch (depth > 0): a
    batch failing mid-pipeline reroutes to the fault stream EXACTLY once
    — the batch the in-flight entry belongs to, not the batch being
    processed when the failure materializes — and later batches flow."""
    rt = mgr.create_app_runtime("""
        @app:devicePipeline('2')
        @OnError(action='stream')
        define stream S (sym string, p double);
        from S#window.length(4) select sym, sum(p) as s group by sym
            insert into Out;
        from !S select sym, _error insert into F;
    """)
    assert rt._plans[0]._pipe.depth == 2
    rows, faults = collect(rt, "Out"), collect(rt, "F")
    rt.fault_injector = FaultInjector(seed=1, counts={"d2h": 1})
    h = rt.input_handler("S")
    for k in range(6):              # one micro-batch per send_batch call
        h.send_batch({"sym": [f"B{k}_{i}" for i in range(3)],
                      "p": np.arange(3, dtype=float)})
    rt.flush()
    # batch 0's entry fails at materialization (while later batches are
    # in flight); its 3 events route to !S once, the other 5 batches
    # deliver normally
    assert len(faults) == 3
    assert all(sym.startswith("B0_") for sym, _err in faults)
    assert all("d2h" in err for _sym, err in faults)
    assert len(rows) == 5 * 3


# ---------------------------------------------------------------------------
# graceful degradation: halving -> interpreter quarantine, byte-identical
# ---------------------------------------------------------------------------

PATTERN_APP = """
@app:devicePatterns('prefer')
@OnError(action='store')
define stream S (sym string, p double);
from every a=S[p > 120] -> b=S[p < 80] within 1 sec
select a.sym as s1, b.sym as s2 insert into Out;
"""

JOIN_APP = """
@OnError(action='store')
define stream S (sym string, p double);
define stream T (sym string, v int);
from S#window.length(8) as a join T#window.length(8) as b on a.sym == b.sym
select a.sym as sym, a.p as p, b.v as v insert into Out;
"""


def _run_window(mgr, injector=None):
    rt = mgr.create_app_runtime(WIN_APP.format(action="store", extra=""))
    rt.fault_injector = injector
    rows = collect(rt, "Out")
    h = rt.input_handler("S")
    for k in range(4):
        h.send([(f"K{j % 3}", float(j + k)) for j in range(8)])
        rt.flush()
    return rt, rows


def _run_pattern(mgr, injector=None):
    rt = mgr.create_app_runtime(PATTERN_APP)
    rt.fault_injector = injector
    rows = collect(rt, "Out")
    h = rt.input_handler("S")
    rng = np.random.default_rng(0)
    ts0 = 1_700_000_000_000
    for k in range(4):
        n = 64
        h.send_batch({"sym": [f"K{i % 4}" for i in range(n)],
                      "p": rng.uniform(60, 140, n).round(1)},
                     np.arange(ts0 + k * n * 10, ts0 + (k + 1) * n * 10, 10))
        rt.flush()
    return rt, rows


def _run_join(mgr, injector=None):
    rt = mgr.create_app_runtime(JOIN_APP)
    rt.fault_injector = injector
    rows = collect(rt, "Out")
    hs, ht = rt.input_handler("S"), rt.input_handler("T")
    for k in range(4):
        hs.send([(f"K{i % 3}", float(i + k)) for i in range(6)])
        ht.send([(f"K{i % 3}", i * 10 + k) for i in range(6)])
        rt.flush()
    return rt, sorted(rows)


@pytest.mark.parametrize("runner,plan_cls", [
    (_run_window, "DeviceWindowAggPlan"),
    (_run_pattern, "DevicePatternPlan"),
    (_run_join, "DeviceJoinPlan"),
])
def test_degradation_halving_is_lossless(mgr, runner, plan_cls):
    """Transient resource exhaustion at dispatch: the ladder halves the
    work and retries — outputs byte-identical to a fault-free run, no
    quarantine."""
    rt0, clean = runner(mgr)
    assert type(rt0._plans[0]).__name__ == plan_cls
    rt, chaos = runner(mgr, FaultInjector(seed=3, counts={"dispatch": 2}))
    assert chaos == clean and len(clean) > 0
    lad = rt._ladders[rt0._plans[0].name]
    assert lad.halvings >= 1 and not lad.quarantined
    assert "degraded_plans" not in rt.statistics()


@pytest.mark.parametrize("runner", [_run_window, _run_pattern, _run_join])
def test_degradation_quarantine_byte_identical(mgr, runner):
    """Persistent resource exhaustion: after K consecutive failures the
    plan is quarantined onto the interpreter path — match output
    byte-identical to a fault-free (device) run, surfaced in
    statistics()."""
    _rt0, clean = runner(mgr)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rt, chaos = runner(mgr, FaultInjector(seed=3,
                                              counts={"dispatch": 100_000}))
    assert chaos == clean and len(clean) > 0
    rep = rt.statistics()
    assert rep["degraded_plans"] == [rt._plans[0].name]
    name = rep["degraded_plans"][0]
    assert rep["device"][name]["quarantined"] is True
    # quarantined plan is the interpreter twin now
    assert type(rt._plan_by_name[name]).__name__.startswith("Interp")
    # prometheus carries the gauge
    assert "siddhi_tpu_degraded_plans" in rt.stats.prometheus()


def test_snapshot_after_quarantine_restores(mgr):
    """A snapshot taken after a quarantine carries interp-format plan
    state; restore must re-quarantine the fresh runtime's device plan
    before loading it (not crash with a state-shape mismatch)."""
    app = WIN_APP.format(action="store", extra="")
    rt = mgr.create_app_runtime(app)
    rows = collect(rt, "Out")
    rt.fault_injector = FaultInjector(seed=7, counts={"dispatch": 10 ** 6})
    h = rt.input_handler("S")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        for k in range(3):
            h.send([(f"K{j % 2}", float(j + k)) for j in range(4)])
            rt.flush()
    assert rt.statistics()["degraded_plans"] == ["query_0"]
    snap = rt.snapshot()
    rt2 = mgr.create_app_runtime(app)
    rows2 = collect(rt2, "Out")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        rt2.restore(snap)
    assert rt2.statistics()["degraded_plans"] == ["query_0"]
    h2 = rt2.input_handler("S")
    h2.send([("K0", 100.0)])
    rt2.flush()
    # window continuity across the restore: the restored (interp) window
    # still holds the pre-snapshot K0 events
    assert rows2 == [("K0", 104.0)]


def test_quarantine_counts_as_consecutive_not_total(mgr):
    """Non-consecutive resource faults (success in between) never reach
    the quarantine threshold."""
    rt = mgr.create_app_runtime(WIN_APP.format(action="store", extra=""))
    rows = collect(rt, "Out")
    # one fault roughly every other dispatch: consecutive counter resets
    rt.fault_injector = FaultInjector(seed=5, rates={"dispatch": 0.3})
    h = rt.input_handler("S")
    for k in range(12):
        h.send([(f"K{j % 3}", float(j + k)) for j in range(4)])
        rt.flush()
    assert "degraded_plans" not in rt.statistics()
    assert len(rows) == 12 * 4      # one output row per input event


# ---------------------------------------------------------------------------
# sink retry / circuit breaker / replay
# ---------------------------------------------------------------------------

SINK_APP = """
define stream S (x int);
@sink(type='inMemory', topic='{topic}', on.error='{action}',
      max.retries='2', retry.interval='1 ms', breaker.threshold='3',
      breaker.reset='50 ms')
define stream Out (x int);
from S select x insert into Out;
"""


def test_sink_transient_faults_retried_with_backoff(mgr):
    got = []
    InMemoryBroker.subscribe("t_sink1", lambda m: got.append(m))
    rt = mgr.create_app_runtime(SINK_APP.format(topic="t_sink1",
                                                action="store"))
    rt.fault_injector = FaultInjector(seed=1, counts={"sink.publish": 2})
    rt.start()
    h = rt.input_handler("S")
    h.send((1,))
    rt.flush()
    sink = rt.sinks[0]
    assert got == [(1,)] and sink.retries == 2 and sink.stored == 0
    assert sink.breaker.state == sink.breaker.CLOSED


def test_sink_persistent_faults_stored_breaker_opens_then_replay(mgr):
    got = []
    InMemoryBroker.subscribe("t_sink2", lambda m: got.append(m))
    rt = mgr.create_app_runtime(SINK_APP.format(topic="t_sink2",
                                                action="store"))
    rt.fault_injector = FaultInjector(seed=1,
                                      counts={"sink.publish": 10_000})
    rt.start()
    h = rt.input_handler("S")
    for i in range(6):
        h.send((i,))
        rt.flush()
    sink = rt.sinks[0]
    assert got == [] and sink.stored == 6
    assert sink.breaker.state == sink.breaker.OPEN
    assert len(rt.error_store) == 6
    m = sink.metrics()
    assert m["circuit_state"] == 2 and m["circuit_opens"] >= 1
    # transport recovers: replay delivers everything — zero event loss
    rt.fault_injector = None
    res = rt.error_store.replay(rt)
    assert res["replayed"] == 6 and res["remaining"] == 0
    assert sorted(p[0] for p in got) == list(range(6))
    rep = rt.statistics()
    assert rep["sinks"]["Out[0]"]["stored"] == 6
    assert "siddhi_tpu_sink_circuit_state" in rt.stats.prometheus()


def test_sink_without_onerror_keeps_failfast(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        @sink(type='inMemory', topic='t_sink3')
        define stream Out (x int);
        from S select x insert into Out;
    """)
    rt.fault_injector = FaultInjector(seed=1, counts={"sink.publish": 1})
    rt.start()
    h = rt.input_handler("S")
    with pytest.raises(InjectedFault):
        h.send((1,))
        rt.flush()


def test_source_connect_retry_backoff(mgr):
    rt = mgr.create_app_runtime("""
        @source(type='inMemory', topic='t_conn')
        define stream S (x int);
        from S select x insert into O;
    """)
    rt.fault_injector = FaultInjector(seed=1, counts={"source.connect": 2})
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rt.start()                  # 2 failures, then connects
    assert rt.sources[0].connected
    assert sum("retrying in" in str(x.message) for x in w) == 2
    got = collect(rt, "O")
    InMemoryBroker.publish("t_conn", (7,))
    assert got == [(7,)]


def test_source_dropped_events_counter(mgr):
    rt = mgr.create_app_runtime("""
        @source(type='inMemory', topic='t_drop', @map(type='json'))
        define stream S (x int);
        from S select x insert into O;
    """)
    rt.start()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        InMemoryBroker.publish("t_drop", "{not json")
        InMemoryBroker.publish("t_drop", "also bad")
    rep = rt.statistics()
    assert rep["sources"]["S"]["dropped_events"] == 2
    assert rep["faults"]["S"]["source.drop"] == 2
    assert 'siddhi_tpu_source_dropped_events_total{app="test",stream="S"} 2' \
        in rt.stats.prometheus().replace(f'app="{rt.app.name}"', 'app="test"')


# ---------------------------------------------------------------------------
# /siddhi/errors service endpoints
# ---------------------------------------------------------------------------

def test_service_errors_endpoints():
    import json
    import urllib.request
    from siddhi_tpu.service import SiddhiService
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        app = ("@app:name('E')\n"
               "@OnError(action='store')\n"
               "define stream S (x int);\n"
               "from S#window.length(2) select sum(x) as s insert into Out;\n")
        req = urllib.request.Request(f"{base}/siddhi/artifact/deploy",
                                     data=app.encode(), method="POST")
        urllib.request.urlopen(req).read()
        rt = svc.runtimes["E"]
        rt.fault_injector = FaultInjector(
            seed=1, counts={"dispatch": 1}, kinds={"dispatch": "fault"})
        rt.send("S", (5,))
        rt.flush()
        with urllib.request.urlopen(
                f"{base}/siddhi/errors?siddhiApp=E") as r:
            body = json.loads(r.read())
        assert len(body["errors"]) == 1
        ent = body["errors"][0]
        assert ent["stream"] == "S" and ent["events"] == [[ent["events"][0][0],
                                                           [5]]]
        # replay through POST (injector burst exhausted -> succeeds)
        req = urllib.request.Request(
            f"{base}/siddhi/errors",
            data=json.dumps({"app": "E", "action": "replay"}).encode(),
            method="POST")
        res = json.loads(urllib.request.urlopen(req).read())
        assert res["replayed"] == 1 and res["remaining"] == 0
        # 404 on unknown app
        try:
            urllib.request.urlopen(f"{base}/siddhi/errors?siddhiApp=nope")
            assert False
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        svc.stop()


def test_pipeline_keeps_ready_results_on_later_failure():
    """A later in-flight entry failing to materialize must not discard
    an earlier entry's already-materialized results (zero silent loss)."""
    from siddhi_tpu.core.pipeline import DispatchPipeline

    def mat(e):
        if e == "bad":
            raise RuntimeError("boom-mat")
        return [e]

    p = DispatchPipeline("t", mat, depth=0)
    p.hold()
    p.origin = ("S", "b1")
    p.push("ok1")
    p.origin = ("S", "b2")
    p.push("bad")
    p.origin = ("S", "b3")
    p.push("ok3")
    with pytest.raises(RuntimeError) as ei:
        p.collect()
    assert ei.value.fault_origin == ("S", "b2")
    # ok1 materialized before the failure and ok3 was still queued:
    # both deliver on the next drain
    assert p.drain() == ["ok1", "ok3"]


def test_source_map_store_capture_is_replayable(mgr):
    """@OnError(action='store') on a source map error captures the raw
    payload with the SOURCE as replay target — replay re-feeds the
    mapper (and a still-broken payload re-captures, never loops as a
    permanent replay failure)."""
    rt2 = mgr.create_app_runtime("""
        @OnError(action='store')
        @source(type='inMemory', topic='t_map_replay2', @map(type='json'))
        define stream S (x int);
        from S select x insert into O;
    """)
    got = collect(rt2, "O")
    rt2.start()
    InMemoryBroker.publish("t_map_replay2", "{broken json")
    assert len(rt2.error_store) == 1
    ent = rt2.error_store.entries("S")[0]
    assert ent.point == "source.map" and ent.payloads == ["{broken json"]
    # still broken: replay re-captures instead of failing forever
    res = rt2.error_store.replay(rt2)
    assert res["replayed"] == 1 and res["failed"] == 0 \
        and res["remaining"] == 1
    # upstream fixed (mapper stub): replay now delivers
    rt2.sources[0].mapper.map = lambda m: [(None, (42,))]
    res = rt2.error_store.replay(rt2)
    assert res["replayed"] == 1 and res["remaining"] == 0
    assert got == [(42,)]


def test_restore_never_applies_standalone_delta(tmp_path):
    """When the only full revision is corrupt, later I- deltas must NOT
    be restored standalone (their op-logs assume the base's state) —
    restore ends with a clean slate, not silent partial state."""
    from siddhi_tpu.core.persistence import \
        IncrementalFileSystemPersistenceStore
    mgr = SiddhiManager()
    store = IncrementalFileSystemPersistenceStore(str(tmp_path))
    mgr.set_persistence_store(store)
    rt = mgr.create_app_runtime(PERSIST_APP)
    h = rt.input_handler("S")
    h.send((1,))
    rt.flush()
    rev_full = rt.persist(incremental=True)
    assert rev_full.startswith("F-")
    h.send((2,))
    rt.flush()
    rev_delta = rt.persist(incremental=True)
    assert rev_delta.startswith("I-")
    import os
    with open(os.path.join(str(tmp_path), "P", f"{rev_full}.snapshot"),
              "wb") as f:
        f.write(b"corrupt full")
    rt2 = mgr.create_app_runtime(PERSIST_APP)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rt2.restore_last_state()
    assert _table_rows(rt2) == []       # nothing restorable — not [2]
    assert store.corrupt_skipped >= 1
    mgr.shutdown()


# ---------------------------------------------------------------------------
# persistence satellites
# ---------------------------------------------------------------------------

PERSIST_APP = """
@app:name('P')
define stream S (x int);
define table T (x int);
from S select x insert into T;
"""


def _table_rows(rt):
    return sorted(row[0] for _ts, row in rt.query("from T select x"))


def test_corrupt_incremental_revision_falls_back(tmp_path):
    from siddhi_tpu.core.persistence import \
        IncrementalFileSystemPersistenceStore
    mgr = SiddhiManager()
    store = IncrementalFileSystemPersistenceStore(str(tmp_path))
    mgr.set_persistence_store(store)
    rt = mgr.create_app_runtime(PERSIST_APP)
    h = rt.input_handler("S")
    h.send((1,))
    rt.flush()
    rt.persist(incremental=True)
    h.send((2,))
    rt.flush()
    rev2 = rt.persist(incremental=True)
    # truncate/corrupt the newest revision (crash mid-write)
    import os
    path = os.path.join(str(tmp_path), "P", f"{rev2}.snapshot")
    with open(path, "wb") as f:
        f.write(b"\x80corrupt")
    rt2 = mgr.create_app_runtime(PERSIST_APP)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rt2.restore_last_state()
    assert _table_rows(rt2) == [1]          # previous revision restored
    assert store.corrupt_skipped >= 1
    assert any("corrupt" in str(x.message) for x in w)
    mgr.shutdown()


def test_corrupt_plain_revision_falls_back(tmp_path):
    from siddhi_tpu.core.persistence import FileSystemPersistenceStore
    mgr = SiddhiManager()
    store = FileSystemPersistenceStore(str(tmp_path))
    mgr.set_persistence_store(store)
    rt = mgr.create_app_runtime(PERSIST_APP)
    h = rt.input_handler("S")
    h.send((1,))
    rt.flush()
    rt.persist()
    h.send((2,))
    rt.flush()
    rev2 = rt.persist()
    import os
    with open(os.path.join(str(tmp_path), "P", f"{rev2}.snapshot"),
              "wb") as f:
        f.write(b"not a pickle")
    rt2 = mgr.create_app_runtime(PERSIST_APP)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rt2.restore_last_state()
    assert _table_rows(rt2) == [1]
    assert rt2.restore_skipped == 1
    assert any("corrupt" in str(x.message) for x in w)
    mgr.shutdown()


def test_async_persistor_prunes_finished_threads():
    from siddhi_tpu.core.persistence import AsyncSnapshotPersistor
    p = AsyncSnapshotPersistor()
    done = []
    for i in range(20):
        t = p.persist(done.append, i)
        t.join(2)
    # persist() prunes dead threads even though wait() was never called
    assert len(p._threads) <= 1
    assert sorted(done) == list(range(20))


def test_persist_save_injection_point(tmp_path):
    from siddhi_tpu.core.persistence import FileSystemPersistenceStore
    mgr = SiddhiManager()
    mgr.set_persistence_store(FileSystemPersistenceStore(str(tmp_path)))
    rt = mgr.create_app_runtime(PERSIST_APP)
    rt.fault_injector = FaultInjector(seed=1, counts={"persist.save": 1})
    with pytest.raises(InjectedFault):
        rt.persist()
    rt.persist()                    # burst exhausted: succeeds
    mgr.shutdown()


# ---------------------------------------------------------------------------
# durability (core/wal.py) injection points: wal.append / wal.fsync /
# wal.truncate — the three chaos boundaries the kill-9 bench rides
# ---------------------------------------------------------------------------

DUR_APP = """
@app:name('W')
@app:durability('batch', dir='%s')
define stream S (x int);
define table T (x int);
from S select x insert into T;
"""


def _dur_rt(mgr, tmp_path, policy="batch"):
    app = DUR_APP % str(tmp_path / "wal")
    if policy != "batch":
        app = app.replace("'batch'", f"'{policy}'")
    rt = mgr.create_app_runtime(app)
    rt.start()
    return rt


def test_wal_append_fault_self_heals_and_rolls_back(mgr, tmp_path):
    """A fault raised mid-append must leave NO scar: the partial record
    is truncated away, the failed frame is not claimed durable, and the
    next append (and a full replay) is clean."""
    rt = _dur_rt(mgr, tmp_path)
    rt.fault_injector = FaultInjector(seed=1, counts={"wal.append": 1})
    with pytest.raises(InjectedFault):
        rt.send("S", (1,))
        rt.flush()
    assert rt.wal.metrics()["appended_frames"] == 0
    rt.fault_injector = None
    rt.send("S", (2,))
    rt.flush()
    assert rt.wal.metrics()["appended_frames"] == 1
    got = list(rt.wal.replay())
    assert len(got) == 1 and got[0][1] == 1      # seq 1, no gap, no scar
    assert rt.wal.corrupt_skipped == 0


def test_wal_append_fault_on_net_feed_captures_whole_frame(mgr, tmp_path):
    """Over the serving plane the zero-loss invariant must hold through
    a WAL append fault: the admitted frame lands WHOLE in the
    ErrorStore (point net.feed), replayable once the log recovers."""
    import numpy as np
    from siddhi_tpu.net import TcpFrameClient
    app = ("@source(type='tcp', port='0')\n" + DUR_APP % str(tmp_path / "w2"))
    rt = mgr.create_app_runtime(app)
    rt.start()
    rt.fault_injector = FaultInjector(seed=1, counts={"wal.append": 1})
    cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "S",
                         TcpFrameClient.cols_of_schema(rt.schemas["S"]))
    for k in range(3):
        cli.send_batch({"x": np.array([k], dtype=np.int32)},
                       np.array([k], dtype=np.int64))
    cli.barrier(timeout=30)
    cli.close()
    stored = rt.error_store.entries("S")
    # exactly ONE capture: the WAL append path stores the frame and
    # marks the exception, so the net.feed guard must not double it
    # (a second entry would double-ingest on replay)
    assert len(stored) == 1 and stored[0].point == "wal.append"
    assert rt.wal.metrics()["appended_frames"] == 2     # the other two
    rt.fault_injector = None
    rep = rt.error_store.replay(rt)
    assert rep["remaining"] == 0
    # replayed frame re-entered ingest -> appended to the WAL after all
    assert rt.wal.metrics()["appended_frames"] == 3
    assert sorted(x[0] for x in rt.tables["T"].all_rows()) == [0, 1, 2]


def test_wal_fsync_fault_rolls_back_record(mgr, tmp_path):
    rt = _dur_rt(mgr, tmp_path, policy="fsync")
    rt.fault_injector = FaultInjector(seed=1, counts={"wal.fsync": 1})
    with pytest.raises(InjectedFault):
        rt.send("S", (1,))
        rt.flush()
    assert rt.fault_injector.stats()["fired"]["wal.fsync"] == 1
    rt.fault_injector = None
    rt.send("S", (2,))
    rt.flush()
    m = rt.wal.metrics()
    assert m["appended_frames"] == 1 and m["fsyncs"] >= 1


def test_wal_truncate_fault_keeps_segments_and_snapshot(mgr, tmp_path):
    """An injected truncation fault must NOT fail the (successful)
    persist — kept segments are redundant, the next barrier retries."""
    from siddhi_tpu.core.persistence import FileSystemPersistenceStore
    mgr.set_persistence_store(
        FileSystemPersistenceStore(str(tmp_path / "snap")))
    rt = _dur_rt(mgr, tmp_path)
    for i in range(3):
        rt.send("S", (i,))
        rt.flush()
    rt.wal.rotate()
    rt.fault_injector = FaultInjector(seed=1, counts={"wal.truncate": 1})
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rev = rt.persist()              # snapshot OK, truncation faulted
    assert rev.watermark == {"S": 3}
    assert any("barrier incomplete" in str(x.message) for x in w)
    assert rt.fault_injector.stats()["fired"]["wal.truncate"] == 1
    assert rt.wal.truncated_segments == 0
    rt.fault_injector = None
    rt.persist()                        # retry: segments go this time
    assert rt.wal.truncated_segments >= 1
