"""Static query analyzer + EXPLAIN plane (docs/ANALYSIS.md).

Four surfaces under test:
  * rule engine (analysis/rules.py): a seeded-violation fixture corpus —
    one app per rule, expected rule ids + severities — and a clean
    corpus that must produce ZERO findings;
  * placement accounting (core/placement.py): every interpreter
    fallback in the build path carries a machine-readable Demotion
    visible through rt.explain(), statistics()["placement"], and the
    Prometheus series (the PR-5 silent-demotion regression class);
  * the CLI (python -m siddhi_tpu.analysis) and the service EXPLAIN
    endpoint (byte-for-byte equal to rt.explain());
  * the self-lint (analysis/selflint.py): SL01 silent-demotion swallow
    and SL02 unguarded shared-counter gates, including the
    strip-one-reason test the acceptance criteria pin.
"""
import json
import os
import warnings

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.analysis import (RULES, StrictAnalysisError, analyze_source,
                                 strict_check)
from siddhi_tpu.analysis.__main__ import extract_apps, main as cli_main
from siddhi_tpu.analysis.selflint import (LOWERING_FILES, lint_package,
                                          lint_source)
from siddhi_tpu.core.placement import DEMOTION_RULES, PlacementLog


def _build(app):
    mgr = SiddhiManager()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        rt = mgr.create_app_runtime(app)
    return mgr, rt


# ---------------------------------------------------------------------------
# rule engine: seeded-violation corpus (one app per rule) + clean corpus
# ---------------------------------------------------------------------------

FIXTURES = {
    "SA01": """
        define stream S (v double);
        define stream Out (a double, b double);
        @info(name='q') from every e1=S[v > 1] -> e2=S[v < 0]
        select e1.v as a, e2.v as b insert into Out;
    """,
    "SA02": """
        define stream S (v double);
        define stream Out (m double);
        @info(name='q') from S select avg(v) as m insert into Out;
    """,
    "SA03": """
        define stream S (k string, v double);
        define stream Out (a double);
        partition with (k of S) begin
          @info(name='q') from S#window.length(5)
          select sum(v) as a insert into Out;
        end;
    """,
    "SA04": """
        define stream S (v double);
        define stream Out (a double, b double);
        @info(name='q') from S[v > 0] select v as a insert into Out;
    """,
    "SA05": """
        define stream S (v double);
        define stream Dead (x int);
        define stream Out (v double);
        @info(name='q') from S[v > 0] select v insert into Out;
    """,
    "SA06": """
        define stream Out (v double);
        @info(name='q') from Nope select v insert into Out;
    """,
    "SA07": """
        define stream S (v double);
        @info(name='q') from S[v > 0] select v insert into Out;
    """,
    "SA08": """
        @app:patternFamily('scan')
        define stream S (v double);
        define stream Out (a double, b double, c double);
        @info(name='q') from every e1=S[v > 1] -> e2=S[v < 0]<0:3>
        -> e3=S[v > 2] within 1 sec
        select e1.v as a, e2[last].v as b, e3.v as c insert into Out;
    """,
    "SA09": """
        @source(type='tcp', rate.limit='0')
        define stream S (v double);
        define stream Out (v double);
        @info(name='q') from S[v > 0] select v insert into Out;
    """,
    "SA10": """
        @app:patternFamily('scan')
        @app:deviceChunkLanes(8)
        define stream S (v double);
        define stream Out (a double, b double);
        @info(name='q') from every e1=S[v > 1] -> e2=S[v > e1.v]
        within 1 sec select e1.v as a, e2.v as b insert into Out;
    """,
    "SA11": """
        define stream L (k string, v double);
        define stream R (k string, w double);
        define stream Out (v double, w double);
        @info(name='q') from L#window.length(5) join R#window.length(5)
        select v, w insert into Out;
    """,
    "SA12": """
        @app:devicePatterns('prefer')
        define stream S (v double);
        define stream Out (a double, b double);
        @info(name='q') from every e1=S[v > 1] -> e2=S[v > e1.v]
        within 1 sec select e1.v as a, e2.v as b insert into Out;
    """,
    "SA13": """
        @app:durability('fsync')
        @source(type='tcp', port='0')
        define stream S (v double);
        define stream Out (v double);
        @info(name='q') from S[v > 0] select v insert into Out;
    """,
    "SA14": """
        @app:durability('batch', dir='/tmp/wal')
        @app:replication('semi-sync', peer='127.0.0.1:7071')
        @source(type='tcp', port='0')
        define stream S (v double);
        define stream Out (v double);
        @info(name='q') from S[v > 0] select v insert into Out;
    """,
    "SA15": """
        define stream Trades (sym string, price double, ts long);
        define aggregation TradeAgg
        from Trades
        select sym, sum(price) as total
        group by sym
        aggregate by ts every sec, min;
    """,
}

CLEAN = [
    """
    define stream S (v double);
    define stream Out (v double);
    @info(name='q') from S[v > 1.0] select v insert into Out;
    """,
    """
    define stream S (k string, v double);
    define stream Mid (v double);
    define stream Out (v double);
    @info(name='q1') from S[v > 0] select v insert into Mid;
    @info(name='q2') from Mid[v > 1] select v insert into Out;
    """,
    """
    @app:partitionCapacity(64)
    define stream Txn (card string, amt int);
    define stream Alerts (a int, b int);
    partition with (card of Txn) begin
      @info(name='p') from every e1=Txn[amt > 100] -> e2=Txn[amt > e1.amt]
      within 1 min select e1.amt as a, e2.amt as b insert into Alerts;
    end;
    """,
]


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_seeded_violation_caught(rule):
    findings = analyze_source(FIXTURES[rule])
    hits = [f for f in findings if f.rule_id == rule]
    assert hits, (rule, [str(f) for f in findings])
    assert all(f.severity == RULES[rule][0] for f in hits)
    # a fixture must not trip UNRELATED error-severity rules (the
    # violation is seeded, everything else in the app is legal)
    assert all(f.rule_id == rule
               for f in findings if f.severity == "error"), \
        [str(f) for f in findings]


@pytest.mark.parametrize("i", range(len(CLEAN)))
def test_clean_app_zero_findings(i):
    assert analyze_source(CLEAN[i]) == []


def test_sa04_lossy_type_mismatch():
    findings = analyze_source("""
        define stream S (v double);
        define stream Out (a int);
        @info(name='q') from S[v > 0] select v as a insert into Out;
    """)
    sa04 = [f for f in findings if f.rule_id == "SA04"]
    assert sa04 and "lossy" in sa04[0].message


@pytest.mark.parametrize("purge", ["@purge(retention='1 hour')",
                                   "@purge(enable='false')"])
def test_sa15_silent_on_purge_decision(purge):
    """Any @purge on the aggregation — a retention span OR an explicit
    opt-out — is a decision; SA15 only fires on the silent default."""
    findings = analyze_source(f"""
        define stream Trades (sym string, price double, ts long);
        {purge}
        define aggregation TradeAgg
        from Trades
        select sym, sum(price) as total
        group by sym
        aggregate by ts every sec, min;
    """)
    assert not [f for f in findings if f.rule_id == "SA15"], \
        [str(f) for f in findings]


def test_sa15_silent_without_group_by():
    # no group key: one row per bucket, bounded by elapsed time alone —
    # not the cardinality blow-up the rule is about
    findings = analyze_source("""
        define stream Trades (sym string, price double, ts long);
        define aggregation TotalAgg
        from Trades
        select sum(price) as total
        aggregate by ts every sec, min;
    """)
    assert not [f for f in findings if f.rule_id == "SA15"], \
        [str(f) for f in findings]


def test_sa08_reuses_classify_reason_strings():
    # the analysis-time verdict is literally a classify_parallel reason
    findings = analyze_source(FIXTURES["SA08"])
    msg = next(f.message for f in findings if f.rule_id == "SA08")
    assert "count quantifier" in msg


# ---------------------------------------------------------------------------
# placement accounting: demotions visible through explain()
# ---------------------------------------------------------------------------

def test_placement_log_basics():
    log = PlacementLog()
    with pytest.raises(ValueError):
        log.demote("q", "D-NOPE", "bogus rule id")
    d1 = log.demote("q", "D-SHAPE", "first reason")
    d2 = log.demote("q", "D-SHAPE", "repeat ignored")
    assert d1 is d2 and len(log) == 1          # idempotent per key
    assert d1.reason == "first reason"
    log.demote("q", "D-FAMILY", "rejected family", alternative="scan")
    log.demote("q2", "D-FUSED", "group too small",
               alternative="fused-lanes")
    # D-FAMILY / D-FUSED do not count as interpreter exits
    assert len(log) == 3 and log.interp_demotions() == 1
    cause = log.demote("q3", "D-FILTER", "lowering failed",
                       cause=RuntimeError("boom"))
    assert cause.to_dict()["cause"] == "RuntimeError: boom"
    assert set(DEMOTION_RULES) >= {d.rule_id for d in log.records()}


def test_windowless_agg_demoted_with_shape_reason():
    mgr, rt = _build("""
        define stream S (v double);
        @info(name='q') from S select avg(v) as m insert into Agg;
    """)
    ent = rt.explain()["queries"]["q"]
    assert ent["path"] == "interpreter"
    dems = ent["demotions"]
    assert dems[0]["rule_id"] == "D-SHAPE"
    assert "aggregation without a window" in dems[0]["reason"]
    mgr.shutdown()


def test_window_plan_demotion_cause_visible():
    """The build.py bare-except regression (satellite 1): a device
    window rejection must surface its cause in explain(), not vanish
    into a silent interpreter fallback."""
    mgr, rt = _build("""
        define stream S (v double);
        define stream Out (m double);
        @info(name='q') from S#window.sort(5, v)
        select max(v) as m insert into Out;
    """)
    ent = rt.explain()["queries"]["q"]
    assert ent["path"] == "interpreter"
    d = next(d for d in ent["demotions"] if d["rule_id"] == "D-WINDOW")
    assert d["reason"] == "window sort"
    assert d["cause"] == "DeviceWindowUnsupported: window sort"
    assert d["alternative"] == "device-window"
    mgr.shutdown()


def test_filter_lowering_failure_reason_visible(monkeypatch):
    """The literal PR-5 bug shape: FilterProjectPlan raising used to be
    swallowed by `except Exception: pass` — now the cause must reach
    explain()."""
    import siddhi_tpu.core.build as build

    def boom(*a, **k):
        raise RuntimeError("synthetic lowering failure")

    monkeypatch.setattr(build, "FilterProjectPlan", boom)
    mgr, rt = _build("""
        define stream S (v double);
        define stream Out (v double);
        @info(name='q') from S[v > 1.0] select v insert into Out;
    """)
    ent = rt.explain()["queries"]["q"]
    assert ent["path"] == "interpreter"
    d = next(d for d in ent["demotions"] if d["rule_id"] == "D-FILTER")
    assert d["cause"] == "RuntimeError: synthetic lowering failure"
    mgr.shutdown()


def test_policy_optout_recorded():
    mgr, rt = _build("""
        @app:deviceFilters('never')
        define stream S (v double);
        define stream Out (v double);
        @info(name='q') from S[v > 1.0] select v insert into Out;
    """)
    d = rt.explain()["queries"]["q"]["demotions"][0]
    assert d["rule_id"] == "D-POLICY"
    assert "deviceFilters" in d["reason"]
    mgr.shutdown()


def test_geometry_provenance_annotation_vs_default():
    mgr, rt = _build("""
        @app:devicePipeline(2)
        define stream S (v double);
        define stream Out (v double);
        @info(name='q') from S[v > 1.0] select v insert into Out;
    """)
    geo = rt.explain()["queries"]["q"]["geometry"]
    assert geo["pipeline_depth"] == {"value": 2, "source": "annotation"}
    mgr.shutdown()
    mgr, rt = _build("""
        define stream S (v double);
        define stream Out (v double);
        @info(name='q') from S[v > 1.0] select v insert into Out;
    """)
    geo = rt.explain()["queries"]["q"]["geometry"]
    assert geo["pipeline_depth"]["source"] == "default"
    mgr.shutdown()


def test_ineligible_family_reasons_reach_explain():
    """Satellite: every classify_parallel reason string for the 5
    ineligible shapes is reachable through rt.explain() — both in the
    per-family rejection map and as a D-FAMILY demotion."""
    from test_plan_families import HEAD, INELIGIBLE
    force = ("@app:patternFamily('scan')\n@app:deviceChunkLanes(0)\n"
             "@app:devicePatterns('always')\n")
    for name, (q, frag) in INELIGIBLE.items():
        mgr, rt = _build(force + HEAD + q)
        ent = rt.explain()["queries"]["q"]
        assert ent["path"] == "device" and ent["family"] == "seq", \
            (name, ent)
        for fam in ("scan", "dfa"):
            assert frag.lower() in str(ent["rejected"][fam]).lower(), \
                (name, fam, ent["rejected"])
        dem = [d for d in ent["demotions"] if d["rule_id"] == "D-FAMILY"]
        assert dem and frag.lower() in dem[0]["reason"].lower(), \
            (name, dem)
        assert dem[0]["alternative"] == "scan"
        mgr.shutdown()


def test_placement_statistics_and_prometheus():
    from siddhi_tpu.core.telemetry import render_prometheus
    mgr, rt = _build("""
        @app:name('P')
        define stream S (v double);
        @info(name='dev') from S[v > 1.0] select v insert into Out;
        @info(name='host') from S select avg(v) as m insert into Agg;
    """)
    pl = rt.statistics()["placement"]
    assert pl["device"] == 1 and pl["interpreter"] == 1
    assert pl["interp_demotions"] == 1
    assert pl["queries"]["dev"]["path"] == "device"
    assert pl["queries"]["host"] == {"path": "interpreter",
                                     "kind": "single", "demotions": 1}
    text = render_prometheus({"P": rt.stats.report()})
    assert 'siddhi_tpu_interp_demotions{app="P"} 1' in text
    assert ('siddhi_tpu_placement_queries{app="P",path="device"} 1'
            in text)
    assert ('siddhi_tpu_placement_queries{app="P",path="interpreter"} 1'
            in text)
    assert ('siddhi_tpu_query_placement{app="P",query="dev",'
            'path="device"} 1' in text)
    mgr.shutdown()


def test_strict_analysis_blocks_warn_findings():
    app = """
        @app:name('Strict') @app:strictAnalysis
        define stream S (v double);
        @info(name='q') from S select avg(v) as m insert into Out;
    """
    with pytest.raises(StrictAnalysisError) as ei:
        SiddhiManager().create_app_runtime(app)
    assert any(f.rule_id == "SA02" for f in ei.value.findings)
    # the same app without the annotation deploys (with findings)
    mgr, rt = _build(app.replace("@app:strictAnalysis", ""))
    assert strict_check.__module__  # imported surface stays stable
    mgr.shutdown()


def test_strict_analysis_passes_clean_app():
    mgr, rt = _build("@app:name('C') @app:strictAnalysis\n" + CLEAN[0])
    assert rt.explain()["placement"]["interp_demotions"] == 0
    mgr.shutdown()


# ---------------------------------------------------------------------------
# CLI: python -m siddhi_tpu.analysis
# ---------------------------------------------------------------------------

def test_cli_json_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.siddhi"
    bad.write_text(FIXTURES["SA06"])
    assert cli_main(["--json", str(bad)]) == 1        # error severity
    out = json.loads(capsys.readouterr().out)
    assert out["severities"]["error"] == 1
    assert out["apps"][0]["findings"][0]["rule_id"] == "SA06"

    clean = tmp_path / "clean.siddhi"
    clean.write_text(CLEAN[0])
    assert cli_main(["--json", str(clean)]) == 0
    capsys.readouterr()

    warn = tmp_path / "warn.siddhi"
    warn.write_text(FIXTURES["SA02"])
    assert cli_main([str(warn)]) == 0                 # warn passes...
    capsys.readouterr()
    assert cli_main(["--strict", str(warn)]) == 1     # ...unless strict
    capsys.readouterr()


def test_cli_expect_pinning(tmp_path, capsys):
    p = tmp_path / "warn.siddhi"
    p.write_text(FIXTURES["SA02"])
    assert cli_main(["--expect", "SA02", str(p)]) == 0
    capsys.readouterr()
    assert cli_main(["--expect", "SA02,SA05", str(p)]) == 1
    capsys.readouterr()


def test_cli_extracts_apps_from_python_samples(tmp_path):
    py = tmp_path / "sample.py"
    py.write_text(
        'X = 1\nAPP = """\ndefine stream S (v double);\n'
        '@info(name=\'q\') from S[v > 0] select v insert into Out;\n"""\n'
        'OTHER = "not an app"\n')
    apps = extract_apps(str(py))
    assert len(apps) == 1 and apps[0][0].endswith("sample.py:APP")
    assert "define stream S" in apps[0][1]


def test_cli_explain_matches_runtime_explain(tmp_path, capsys):
    """The CLI's --explain JSON is the same EXPLAIN plane rt.explain()
    serves — including every ineligible-shape reason (satellite: CLI
    half of the classify_parallel reason matrix)."""
    from test_plan_families import HEAD, INELIGIBLE
    force = ("@app:patternFamily('scan')\n@app:deviceChunkLanes(0)\n"
             "@app:devicePatterns('always')\n")
    paths = []
    for name, (q, _frag) in sorted(INELIGIBLE.items()):
        p = tmp_path / f"{name}.siddhi"
        p.write_text(force + HEAD + q)
        paths.append(str(p))
    rc = cli_main(["--json", "--explain"] + paths)
    out = json.loads(capsys.readouterr().out)
    assert rc == 0                  # warns (SA08/SA10) don't fail plain
    by_src = {os.path.basename(e["source"]): e for e in out["apps"]}
    for name, (q, frag) in INELIGIBLE.items():
        entry = by_src[f"{name}.siddhi"]
        ex = entry["explain"]
        qd = ex["queries"]["q"]
        assert frag.lower() in str(qd["rejected"]["scan"]).lower(), \
            (name, qd)
        # the forced-but-ineligible annotation ALSO fires SA08 at
        # analysis time, before any build happens
        assert any(f["rule_id"] == "SA08" for f in entry["findings"]), \
            (name, entry["findings"])
        mgr, rt = _build(force + HEAD + q)
        assert ex == rt.explain(), name       # CLI == runtime, verbatim
        mgr.shutdown()


def test_cli_self_lint_gate_is_green(capsys):
    assert cli_main(["--self"]) == 0
    capsys.readouterr()


# ---------------------------------------------------------------------------
# self-lint: SL01 / SL02
# ---------------------------------------------------------------------------

SWALLOW = """
def plan(rt, name):
    try:
        lower()
    except Exception:
        pass
"""

SWALLOW_DEMOTED = """
def plan(rt, name):
    try:
        lower()
    except Exception as e:
        rt.placement.demote(name, "D-FILTER", "lowering failed", cause=e)
"""

SWALLOW_RERAISED = """
def plan(rt, name):
    try:
        lower()
    except Exception:
        raise
"""

SWALLOW_PRAGMA = """
def plan(rt, name):
    try:
        lower()
    except Exception:   # lint: allow-swallow (best-effort probe)
        pass
"""


def test_sl01_swallow_variants():
    assert [f.rule_id for f in lint_source(SWALLOW, "core/build.py")] \
        == ["SL01"]
    assert lint_source(SWALLOW_DEMOTED, "core/build.py") == []
    assert lint_source(SWALLOW_RERAISED, "core/build.py") == []
    assert lint_source(SWALLOW_PRAGMA, "core/build.py") == []
    # outside the lowering-path file set the swallow is out of scope
    assert lint_source(SWALLOW, "net/frame.py") == []


COUNTER_RACE = """
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.frames_total = 0
    def bump(self):
        self.frames_total += 1
"""


def test_sl02_counter_variants():
    assert [f.rule_id for f in lint_source(COUNTER_RACE, "net/x.py")] \
        == ["SL02"]
    guarded = COUNTER_RACE.replace(
        "        self.frames_total += 1",
        "        with self._lock:\n            self.frames_total += 1")
    assert lint_source(guarded, "net/x.py") == []
    locked_name = COUNTER_RACE.replace("def bump", "def bump_locked")
    assert lint_source(locked_name, "net/x.py") == []
    pragma = COUNTER_RACE.replace(
        "self.frames_total += 1",
        "self.frames_total += 1   # lint: unlocked-ok (single writer)")
    assert lint_source(pragma, "net/x.py") == []
    # a class that owns no lock makes no locking promise
    no_lock = COUNTER_RACE.replace(
        "        self._lock = threading.Lock()\n", "")
    assert lint_source(no_lock, "net/x.py") == []


def test_self_lint_package_is_clean():
    assert [str(f) for f in lint_package()] == []


def test_self_lint_catches_stripped_reason():
    """Acceptance criterion: strip ONE recorded Demotion out of a real
    lowering file and the lint must catch the now-silent swallow."""
    import ast as pyast
    from siddhi_tpu.core import build
    path = build.__file__
    src = open(path, encoding="utf-8").read()
    assert "core/build.py" in LOWERING_FILES
    assert not lint_source(src, "core/build.py"), "gate not green?"
    tree = pyast.parse(src)
    victim = None
    for node in pyast.walk(tree):
        if not isinstance(node, pyast.ExceptHandler):
            continue
        body_src = "\n".join(pyast.unparse(s) for s in node.body)
        if "demote" in body_src and not any(
                isinstance(n, pyast.Raise) for stmt in node.body
                for n in pyast.walk(stmt)):
            victim = node
            break
    assert victim is not None, "build.py has no demoting handler?"
    lines = src.splitlines(True)
    for i in range(victim.lineno - 1, victim.end_lineno):
        lines[i] = lines[i].replace("demote", "demoted_no_more")
    stripped = "".join(lines)
    findings = lint_source(stripped, "core/build.py")
    assert [f.rule_id for f in findings] == ["SL01"], \
        [str(f) for f in findings]
    assert f"core/build.py:{victim.lineno}" == findings[0].subject


def test_quarantine_records_demotion_in_explain():
    """The runtime half of the taxonomy: a degradation-ladder
    quarantine (docs/RELIABILITY.md) must surface as a D-QUARANTINE
    demotion — the query reads `interpreter` in explain() with the
    device failure as its cause."""
    from siddhi_tpu.core.faults import FaultInjector
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime("""
        @OnError(action='store')
        define stream S (sym string, p double);
        @info(name='q') from S#window.length(4)
        select sum(p) as sp insert into Out;
    """)
    assert rt.explain()["queries"]["q"]["path"] == "device"
    rt.fault_injector = FaultInjector(seed=3,
                                      counts={"dispatch": 100_000})
    h = rt.input_handler("S")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        for k in range(4):
            h.send([(f"K{j % 3}", float(j + k)) for j in range(8)])
            rt.flush()
    ent = rt.explain()["queries"]["q"]
    assert ent["path"] == "interpreter"
    d = next(d for d in ent["demotions"]
             if d["rule_id"] == "D-QUARANTINE")
    assert "consecutive device dispatch failures" in d["reason"]
    assert "RESOURCE_EXHAUSTED" in d["cause"]
    pl = rt.statistics()["placement"]
    assert pl["interpreter"] == 1 and pl["interp_demotions"] == 1
    mgr.shutdown()


def test_partition_clones_aggregate_per_query():
    """Per-key host-clone plans (`<base>#<inst>`) must collapse onto
    their base query in placement/explain — counts are per QUERY, and
    the per-query Prometheus label set must not scale with partition
    key cardinality."""
    from siddhi_tpu.core.telemetry import render_prometheus
    mgr, rt = _build("""
        @app:name('PK')
        define stream S (k string, v double);
        define stream Out (a double);
        partition with (k of S) begin
          @info(name='q') from S#window.length(4)
          select sum(v) as a insert into Out;
        end;
    """)
    h = rt.input_handler("S")
    h.send([(f"K{i}", float(i)) for i in range(4)])   # 4 key instances
    rt.flush()
    pl = rt.statistics()["placement"]
    assert pl["interpreter"] + pl["device"] == 2      # group + q, not 5
    assert set(pl["queries"]) == {"#partition_0", "q"}
    assert pl["queries"]["q"]["instances"] == 4
    ex = rt.explain()
    assert set(ex["queries"]) == {"#partition_0", "q"}
    assert ex["queries"]["q"]["instances"] == 4
    text = render_prometheus({"PK": rt.stats.report()})
    assert text.count('siddhi_tpu_query_placement{app="PK",query="q"') == 1
    mgr.shutdown()
