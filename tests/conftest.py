"""Test config: force CPU backend with 8 virtual devices so sharding tests
exercise a multi-chip mesh without TPU hardware (bench.py uses the real chip).

Note: the environment's sitecustomize imports jax with the TPU platform
pinned before conftest runs, so env vars alone don't stick — we must also
update jax.config (safe: no backend computation has run yet)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax
jax.config.update("jax_platforms", "cpu")

import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
