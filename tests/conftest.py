"""Test config: force CPU backend with 8 virtual devices so sharding tests
exercise a multi-chip mesh without TPU hardware (bench.py uses the real chip)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
