"""Test config: force CPU backend with 8 virtual devices so sharding tests
exercise a multi-chip mesh without TPU hardware (bench.py uses the real chip).

TPU lane: `SIDDHI_TEST_TPU=1 python -m pytest tests/ -q` keeps the real
chip instead, running the whole suite against device numerics (f64
emulation, scatter mode="drop", tunnel transfer behavior).  Mesh tests
that need 8 devices skip themselves on a 1-chip host.

Note: the environment's sitecustomize imports jax with the TPU platform
pinned before conftest runs, so env vars alone don't stick — we must also
update jax.config (safe: no backend computation has run yet)."""
import os

TPU_LANE = bool(os.environ.get("SIDDHI_TEST_TPU"))

if not TPU_LANE:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    import jax
    jax.config.update("jax_platforms", "cpu")
else:
    import jax
    import pytest

    def pytest_collection_modifyitems(config, items):
        if len(jax.devices()) >= 8:
            return
        skip = pytest.mark.skip(reason="TPU lane: needs an 8-device mesh")
        for item in items:
            if "test_mesh_async" in str(item.fspath):
                item.add_marker(skip)

def pytest_configure(config):
    # tier-1 CI runs `-m 'not slow'` (ROADMAP.md): long fuzz/paced-load
    # tests ride the full suite only, keeping tier-1 under its time box
    config.addinivalue_line(
        "markers", "slow: long-running (fuzz tapes, paced load); "
        "excluded from tier-1 via -m 'not slow'")


import pytest


@pytest.fixture(scope="session", autouse=True)
def _siddhi_thread_leak_gate():
    """Thread-leak gate (docs/ANALYSIS.md "Concurrency self-analysis"):
    every engine thread is named `siddhi-<role>` (the SL06 lint holds
    that), so a NON-daemon siddhi-* thread still alive after the whole
    session tore its runtimes/services down is a leak — some shutdown
    path stopped joining it.  Daemon threads are exempt (process exit
    reaps them by design).  A failure here fails tier-1."""
    yield
    import threading
    import time
    deadline = time.time() + 2.0        # teardown joins may still settle

    def _leaky(t):
        if not t.name.startswith("siddhi-") or not t.is_alive():
            return False
        # the trace exporter (core/tracing.py) is daemonized BUT must
        # never outlive the session: tracer.close() joins it on
        # shutdown, and an unclosed tracer's exporter self-terminates
        # after ~0.5 s idle — either way it must be gone by now.  The
        # phase profiler (core/profiler.py) spawns no threads by
        # design; the gate pins that contract too
        if t.name in ("siddhi-trace-export", "siddhi-profile"):
            return True
        return not t.daemon

    while True:
        leaked = [t for t in threading.enumerate() if _leaky(t)]
        if not leaked or time.time() >= deadline:
            break
        time.sleep(0.1)
    assert not leaked, (
        "siddhi-* threads outlived the session (a shutdown "
        f"path stopped joining them): {sorted(t.name for t in leaked)}")


# isolate the execution-geometry tuning cache (core/autotune.py): the
# suite must neither trust nor pollute a developer's persisted winners
if "SIDDHI_TUNE_CACHE" not in os.environ:
    import tempfile
    os.environ["SIDDHI_TUNE_CACHE"] = os.path.join(
        tempfile.mkdtemp(prefix="siddhi_tune_test_"), "tuning.json")

import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
