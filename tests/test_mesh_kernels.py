"""Mesh sharding for the non-pattern device kernels (VERDICT r3 #4):
window-agg (batch axis sharded), device incremental aggregation (event
shards + commutative partial merge), and fused multi-query lanes.  The
driver's dryrun_multichip runs the same checks; these keep them green
in the suite's 8-virtual-device CPU lane."""
import importlib.util
import os

import jax
import pytest

need8 = pytest.mark.skipif(len(jax.devices()) < 8,
                           reason="needs an 8-device mesh")

_spec = importlib.util.spec_from_file_location(
    "graft_entry", os.path.join(os.path.dirname(__file__), "..",
                                "__graft_entry__.py"))
ge = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(ge)


@need8
def test_window_agg_sharded_matches_host():
    ge._dryrun_window_agg(8)


@need8
def test_incremental_agg_sharded_matches_host():
    ge._dryrun_incremental_agg(8)


@need8
def test_multi_query_lanes_sharded_match_host():
    ge._dryrun_multi_query(8)


@need8
def test_chunked_halo_lanes_sharded_match_host():
    ge._dryrun_chunked_halo(8)


@need8
def test_multihost_2d_mesh_matches_1d():
    ge.dryrun_multihost(2, 8)
