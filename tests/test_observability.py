"""Statistics, debugger, and extension SPI (stream functions, windows,
aggregators).  Reference test surface: managment/StatisticsTestCase,
debugger/SiddhiDebuggerTestCase, query/extension/*."""
import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def collect(rt, sid):
    out = []
    rt.add_callback(sid, lambda evs: out.extend(e.data for e in evs))
    return out


def test_statistics_tracking(mgr):
    rt = mgr.create_app_runtime("""
        @app:statistics('true')
        define stream S (x int);
        @info(name='q1') from S[x > 0] select x insert into O;
    """)
    collect(rt, "O")
    rt.input_handler("S").send([(i,) for i in range(100)])
    rt.flush()
    rep = rt.statistics()
    assert rep["streams"]["S"]["events"] == 100
    assert rep["queries"]["q1"]["events"] == 100
    assert rep["queries"]["q1"]["seconds"] > 0


def test_statistics_runtime_toggle(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        from S select x insert into O;
    """)
    collect(rt, "O")
    rt.input_handler("S").send((1,))
    rt.flush()
    assert rt.statistics()["streams"] == {}     # off by default
    rt.enable_stats(True)
    rt.input_handler("S").send((2,))
    rt.flush()
    assert rt.statistics()["streams"]["S"]["events"] == 1


def test_debugger_breakpoints(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        @info(name='q1') from S[x > 5] select x * 2 as y insert into O;
    """)
    collect(rt, "O")
    dbg = rt.debug()
    hits = []
    dbg.set_callback(lambda q, pt, evs: hits.append((q, pt,
                                                     [e.data for e in evs])))
    dbg.acquire_breakpoint("q1", dbg.IN)
    dbg.acquire_breakpoint("q1", dbg.OUT)
    rt.input_handler("S").send([(3,), (10,)])
    rt.flush()
    assert ("q1", "in", [(3,), (10,)]) in hits
    assert ("q1", "out", [(20,)]) in hits
    dbg.release_all()
    hits.clear()
    rt.input_handler("S").send((7,))
    rt.flush()
    assert hits == []


def test_log_stream_function(mgr, capsys):
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        @info(name='q') from S#log('seen') select x insert into O;
    """)
    out = collect(rt, "O")
    rt.input_handler("S").send((1,))
    rt.flush()
    assert out == [(1,)]
    assert "seen" in capsys.readouterr().out


def test_pol2cart_stream_function(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (theta double, rho double);
        from S#pol2cart(theta, rho) select x, y insert into O;
    """)
    out = collect(rt, "O")
    rt.input_handler("S").send((0.0, 2.0))
    rt.flush()
    x, y = out[0]
    assert abs(x - 2.0) < 1e-9 and abs(y) < 1e-9


def test_custom_stream_function(mgr):
    from siddhi_tpu.interp.engine import register_stream_function

    def explode(args, ctx, in_schema, qname):
        def fn(ev):
            return [ev.data, ev.data]          # duplicate every event
        return in_schema, fn
    register_stream_function("explode", explode, "test")

    rt = mgr.create_app_runtime("""
        define stream S (x int);
        from S#test:explode() select x insert into O;
    """)
    out = collect(rt, "O")
    rt.input_handler("S").send((4,))
    rt.flush()
    assert out == [(4,), (4,)]


def test_custom_aggregator(mgr):
    from siddhi_tpu.interp.aggregators import Aggregator, register_aggregator
    from siddhi_tpu.query.ast import AttrType

    class ConcatAgg(Aggregator):
        type = AttrType.STRING

        def __init__(self, in_type):
            self.parts = []

        def add(self, v):
            self.parts.append(str(v))

        def remove(self, v):
            if str(v) in self.parts:
                self.parts.remove(str(v))

        def reset(self):
            self.parts = []

        def value(self):
            return "".join(self.parts)

        def state(self):
            return {"parts": list(self.parts)}

        def restore(self, st):
            self.parts = list(st["parts"])

    register_aggregator("strConcat", ConcatAgg)
    rt = mgr.create_app_runtime("""
        define stream S (s string);
        from S select strConcat(s) as joined insert into O;
    """)
    out = collect(rt, "O")
    rt.input_handler("S").send([("a",), ("b",)])
    rt.flush()
    assert out == [("a",), ("ab",)]


def test_custom_window_type(mgr):
    from siddhi_tpu.interp.engine import register_window_type
    from siddhi_tpu.interp import windows as W

    def first_n(args, ctx, schema):
        n = int(args[0].value)

        class FirstN(W.Window):
            def __init__(self):
                self.seen = 0

            def process(self, ev, now_ms):
                self.seen += 1
                return [(W.CURRENT, ev)] if self.seen <= n else []

            def state(self):
                return {"seen": self.seen}

            def restore(self, st):
                self.seen = st["seen"]
        return FirstN()
    register_window_type("firstN", first_n)

    rt = mgr.create_app_runtime("""
        define stream S (x int);
        from S#window.firstN(2) select x insert into O;
    """)
    out = collect(rt, "O")
    rt.input_handler("S").send([(1,), (2,), (3,)])
    rt.flush()
    assert out == [(1,), (2,)]
