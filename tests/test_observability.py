"""Statistics, debugger, and extension SPI (stream functions, windows,
aggregators).  Reference test surface: managment/StatisticsTestCase,
debugger/SiddhiDebuggerTestCase, query/extension/*."""
import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def collect(rt, sid):
    out = []
    rt.add_callback(sid, lambda evs: out.extend(e.data for e in evs))
    return out


def test_statistics_tracking(mgr):
    rt = mgr.create_app_runtime("""
        @app:statistics('true')
        define stream S (x int);
        @info(name='q1') from S[x > 0] select x insert into O;
    """)
    collect(rt, "O")
    rt.input_handler("S").send([(i,) for i in range(100)])
    rt.flush()
    rep = rt.statistics()
    assert rep["streams"]["S"]["events"] == 100
    assert rep["queries"]["q1"]["events"] == 100
    assert rep["queries"]["q1"]["seconds"] > 0


def test_statistics_runtime_toggle(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        from S select x insert into O;
    """)
    collect(rt, "O")
    rt.input_handler("S").send((1,))
    rt.flush()
    assert rt.statistics()["streams"] == {}     # off by default
    rt.enable_stats(True)
    rt.input_handler("S").send((2,))
    rt.flush()
    assert rt.statistics()["streams"]["S"]["events"] == 1


def test_histogram_quantiles():
    from siddhi_tpu.core.telemetry import Histogram
    h = Histogram()
    assert h.percentile(99) is None          # empty -> None, never 0
    for ms in (1, 2, 5, 10, 100):
        h.record(ms / 1e3)
    # log-bucket bound: reported quantile within ~2^(1/16) of exact
    assert 0.001 <= h.percentile(50) <= 0.0055
    assert 0.05 <= h.percentile(99) <= 0.1001
    assert h.percentile(100) == h.max
    one = Histogram()
    one.record(0.25)
    assert one.percentile(99) == 0.25        # lone sample: exact (clamped)
    one.reset()
    assert one.count == 0 and one.percentile(50) is None


def test_tracker_as_dict_guards():
    """No null-valued keys: throughput/latency OMITTED when nothing was
    timed (a consumer summing report values must not meet None)."""
    from siddhi_tpu.core.telemetry import Tracker
    t = Tracker()
    t.events, t.batches = 10, 1              # counted but never timed
    d = t.as_dict()
    assert "throughput_eps" not in d and "latency_us_per_event" not in d
    assert None not in d.values()
    t.observe(0.5, events=10)
    d = t.as_dict()
    assert d["throughput_eps"] == pytest.approx(20 / 0.5)
    assert d["latency_us_per_event"] == pytest.approx(1e6 * 0.5 / 20)
    zero_ev = Tracker()
    zero_ev.observe(0.5, events=0)           # timed but empty batch
    d = zero_ev.as_dict()
    assert "latency_us_per_event" not in d and None not in d.values()


def test_statistics_percentiles(mgr):
    rt = mgr.create_app_runtime("""
        @app:statistics('true')
        define stream S (x int);
        @info(name='q1') from S[x > 0] select x insert into O;
    """)
    collect(rt, "O")
    import numpy as np
    h = rt.input_handler("S")
    for i in range(4):
        h.send_batch({"x": np.arange(1, 6, dtype=np.int32)})
    rt.flush()
    rep = rt.statistics()
    for scope, key in (("streams", "S"), ("queries", "q1"),
                       ("stages", "scatter")):
        td = rep[scope][key]
        assert td["p50_ms"] <= td["p95_ms"] <= td["p99_ms"]
    assert rep["stages"]["ingest"]["events"] == 20   # columnar ingest span
    assert rep["stages"]["plan"]["batches"] == 1     # build-time span


def test_reporter_spi_register_and_override(mgr):
    from siddhi_tpu.core.telemetry import REPORTERS, register_stats_reporter
    calls_a, calls_b = [], []
    register_stats_reporter("spiTest", lambda app, rep: calls_a.append(app))
    assert REPORTERS["spitest"] is not None          # name lowercased
    register_stats_reporter("SPITest",
                            lambda app, rep: calls_b.append(app))  # override
    rt = mgr.create_app_runtime("""
        @app:name('SpiApp')
        @app:statistics(reporter='spiTest', interval='20 milliseconds')
        define stream S (x int);
        from S select x insert into O;
    """)
    assert rt.stats.reporter is REPORTERS["spitest"]
    rt.stats.reporter("SpiApp", rt.statistics())
    assert calls_b == ["SpiApp"] and calls_a == []   # override won
    del REPORTERS["spitest"]


def test_unknown_reporter_rejected(mgr):
    with pytest.raises(Exception, match="unknown statistics reporter"):
        mgr.create_app_runtime("""
            @app:statistics(reporter='nosuch', interval='1 sec')
            define stream S (x int);
            from S select x insert into O;
        """)


def test_periodic_reporting_and_clean_stop(mgr):
    """@app:statistics(reporter=..., interval=...) starts the pump on
    rt.start() and rt.shutdown() leaves no timer thread behind."""
    import threading
    import time as _time
    from siddhi_tpu.core.telemetry import REPORTERS, register_stats_reporter
    got = []
    register_stats_reporter("trap", lambda app, rep: got.append(rep))
    rt = mgr.create_app_runtime("""
        @app:name('PumpApp')
        @app:statistics(reporter='trap', interval='20 milliseconds')
        define stream S (x int);
        from S select x insert into O;
    """)
    collect(rt, "O")
    rt.start()
    rt.input_handler("S").send((1,))
    rt.flush()
    deadline = _time.time() + 5
    while not got and _time.time() < deadline:
        _time.sleep(0.01)
    assert got, "periodic reporter never fired"
    assert "S" in got[-1]["streams"]
    rt.shutdown()
    assert not [t for t in threading.enumerate()
                if t.name == "siddhi-stats-report" and t.is_alive()], \
        "reporter thread leaked past shutdown()"
    n = len(got)
    _time.sleep(0.08)
    assert len(got) == n                     # pump really stopped
    del REPORTERS["trap"]


def test_prometheus_render(mgr):
    from siddhi_tpu.core.telemetry import render_prometheus
    rt = mgr.create_app_runtime("""
        @app:statistics('true')
        define stream S (x int);
        @info(name='q1') from S[x > 0] select x insert into O;
    """)
    collect(rt, "O")
    rt.input_handler("S").send([(i,) for i in range(1, 8)])
    rt.flush()
    text = render_prometheus({"App1": rt.statistics()})
    assert text.endswith("\n")
    assert 'siddhi_tpu_events_total{app="App1",stream="S"} 7' in text
    assert 'quantile="0.99"' in text
    # exposition format: HELP/TYPE exactly once per metric name
    helps = [ln.split()[2] for ln in text.splitlines()
             if ln.startswith("# HELP")]
    assert len(helps) == len(set(helps))
    for ln in text.splitlines():             # every sample line parses
        if ln.startswith("#") or not ln:
            continue
        val = ln.rsplit(" ", 1)[1]
        assert val == "NaN" or float(val) is not None


def test_chrome_trace_export(mgr, tmp_path):
    import json as _json
    rt = mgr.create_app_runtime("""
        @app:statistics('true')
        define stream S (x int);
        from S[x > 0] select x insert into O;
    """)
    rt.stats.tracer.enabled = True
    collect(rt, "O")
    rt.input_handler("S").send([(1,), (2,)])
    rt.flush()
    path = str(tmp_path / "trace.json")
    n = rt.stats.export_chrome_trace(path)
    evs = _json.loads(open(path).read())
    assert n == len(evs) and n > 0
    assert all(e["ph"] == "X" and e["dur"] >= 0 for e in evs)
    batches = [e for e in evs if e["cat"] == "batch"]
    assert any(e["name"].startswith("S x") for e in batches)


def test_flight_recorder_bounded():
    from siddhi_tpu.core.telemetry import PipelineTracer
    tr = PipelineTracer(capacity=4)
    tr.enabled = True
    for i in range(10):
        tr.add(f"span{i}", float(i), 0.001)
    assert len(tr.traces) == 4               # ring: last N only
    assert tr.traces[0]["label"] == "span6"


def test_device_metrics_sampled(mgr):
    """Device gauges (lane occupancy / frontier width) ride the stats
    report for device pattern plans — sampled at scrape, not per batch."""
    rt = mgr.create_app_runtime("""
        @app:statistics('true')
        @app:devicePatterns('always')
        define stream S (sym string, p double);
        partition with (sym of S) begin
          @info(name='q') from every e1=S[p > 10] -> e2=S[p > e1.p]
            within 1 sec
          select e1.p as a, e2.p as b insert into O;
        end;
    """)
    collect(rt, "O")
    h = rt.input_handler("S")
    ts0 = 1_700_000_000_000
    for rnd in range(2):         # identical rounds: round 2 reuses the
        for i in range(8):       # compiled block -> a `kernel` span
            h.send(("K1" if i % 2 else "K2", 11.0 + i),
                   timestamp=ts0 + (rnd * 8 + i) * 10)
        rt.flush()
    rep = rt.statistics()
    dev = rep["device"]["q"]
    assert dev["lanes_total"] >= 1
    assert dev["compiles"] >= 1 and dev["compile_seconds"] > 0
    assert dev["h2d_bytes"] > 0
    assert {"kernel", "transfer"} <= set(rep["stages"])
    # the compile span is attributed separately from steady-state kernel
    assert rep["stages"]["compile"]["seconds"] > 0


def test_debugger_breakpoints(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        @info(name='q1') from S[x > 5] select x * 2 as y insert into O;
    """)
    collect(rt, "O")
    dbg = rt.debug()
    hits = []
    dbg.set_callback(lambda q, pt, evs: hits.append((q, pt,
                                                     [e.data for e in evs])))
    dbg.acquire_breakpoint("q1", dbg.IN)
    dbg.acquire_breakpoint("q1", dbg.OUT)
    rt.input_handler("S").send([(3,), (10,)])
    rt.flush()
    assert ("q1", "in", [(3,), (10,)]) in hits
    assert ("q1", "out", [(20,)]) in hits
    dbg.release_all()
    hits.clear()
    rt.input_handler("S").send((7,))
    rt.flush()
    assert hits == []


def test_log_stream_function(mgr, capsys):
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        @info(name='q') from S#log('seen') select x insert into O;
    """)
    out = collect(rt, "O")
    rt.input_handler("S").send((1,))
    rt.flush()
    assert out == [(1,)]
    assert "seen" in capsys.readouterr().out


def test_pol2cart_stream_function(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (theta double, rho double);
        from S#pol2cart(theta, rho) select x, y insert into O;
    """)
    out = collect(rt, "O")
    rt.input_handler("S").send((0.0, 2.0))
    rt.flush()
    x, y = out[0]
    assert abs(x - 2.0) < 1e-9 and abs(y) < 1e-9


def test_custom_stream_function(mgr):
    from siddhi_tpu.interp.engine import register_stream_function

    def explode(args, ctx, in_schema, qname):
        def fn(ev):
            return [ev.data, ev.data]          # duplicate every event
        return in_schema, fn
    register_stream_function("explode", explode, "test")

    rt = mgr.create_app_runtime("""
        define stream S (x int);
        from S#test:explode() select x insert into O;
    """)
    out = collect(rt, "O")
    rt.input_handler("S").send((4,))
    rt.flush()
    assert out == [(4,), (4,)]


def test_custom_aggregator(mgr):
    from siddhi_tpu.interp.aggregators import Aggregator, register_aggregator
    from siddhi_tpu.query.ast import AttrType

    class ConcatAgg(Aggregator):
        type = AttrType.STRING

        def __init__(self, in_type):
            self.parts = []

        def add(self, v):
            self.parts.append(str(v))

        def remove(self, v):
            if str(v) in self.parts:
                self.parts.remove(str(v))

        def reset(self):
            self.parts = []

        def value(self):
            return "".join(self.parts)

        def state(self):
            return {"parts": list(self.parts)}

        def restore(self, st):
            self.parts = list(st["parts"])

    register_aggregator("strConcat", ConcatAgg)
    rt = mgr.create_app_runtime("""
        define stream S (s string);
        from S select strConcat(s) as joined insert into O;
    """)
    out = collect(rt, "O")
    rt.input_handler("S").send([("a",), ("b",)])
    rt.flush()
    assert out == [("a",), ("ab",)]


def test_custom_window_type(mgr):
    from siddhi_tpu.interp.engine import register_window_type
    from siddhi_tpu.interp import windows as W

    def first_n(args, ctx, schema):
        n = int(args[0].value)

        class FirstN(W.Window):
            def __init__(self):
                self.seen = 0

            def process(self, ev, now_ms):
                self.seen += 1
                return [(W.CURRENT, ev)] if self.seen <= n else []

            def state(self):
                return {"seen": self.seen}

            def restore(self, st):
                self.seen = st["seen"]
        return FirstN()
    register_window_type("firstN", first_n)

    rt = mgr.create_app_runtime("""
        define stream S (x int);
        from S#window.firstN(2) select x insert into O;
    """)
    out = collect(rt, "O")
    rt.input_handler("S").send([(1,), (2,), (3,)])
    rt.flush()
    assert out == [(1,), (2,)]
