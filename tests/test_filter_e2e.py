"""End-to-end: parse -> compile -> send events -> assert outputs.

Mirrors the reference's integration test pattern (reference:
modules/siddhi-core/src/test/.../query/SimpleQueryValidatorTestCase.java,
FilterTestCase pattern: runtime + callback + InputHandler.send + assert)."""
import pytest

from siddhi_tpu import SiddhiManager


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def test_simple_filter(mgr):
    rt = mgr.create_app_runtime("""
        define stream StockStream (symbol string, price double, volume int);
        @info(name='q1')
        from StockStream[price > 100.0] select symbol, price insert into OutStream;
    """)
    got = []
    rt.add_callback("OutStream", lambda evs: got.extend(evs))
    h = rt.input_handler("StockStream")
    rt.start()
    h.send(("IBM", 75.6, 100))
    h.send(("WSO2", 151.2, 2))
    h.send(("GOOG", 90.0, 3))
    h.send(("MSFT", 500.5, 4))
    rt.flush()
    assert [e.data for e in got] == [("WSO2", 151.2), ("MSFT", 500.5)]


def test_filter_on_string_equality(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (symbol string, price double);
        from S[symbol == 'IBM'] select price insert into O;
    """)
    got = []
    rt.add_callback("O", lambda evs: got.extend(evs))
    h = rt.input_handler("S")
    h.send(("IBM", 1.0))
    h.send(("X", 2.0))
    h.send(("IBM", 3.0))
    rt.flush()
    assert [e.data for e in got] == [(1.0,), (3.0,)]


def test_select_star_and_arithmetic(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (a int, b int);
        from S[a % 2 == 0] select * insert into Evens;
        from S select a + b * 2 as c insert into Calc;
    """)
    evens, calc = [], []
    rt.add_callback("Evens", lambda evs: evens.extend(evs))
    rt.add_callback("Calc", lambda evs: calc.extend(evs))
    h = rt.input_handler("S")
    for a, b in [(1, 10), (2, 20), (3, 30), (4, 40)]:
        h.send((a, b))
    rt.flush()
    assert [e.data for e in evens] == [(2, 20), (4, 40)]
    assert [e.data for e in calc] == [(21,), (42,), (63,), (84,)]


def test_int_division_java_semantics(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (a int, b int);
        from S select a / b as q, a % b as r insert into O;
    """)
    got = []
    rt.add_callback("O", lambda evs: got.extend(evs))
    h = rt.input_handler("S")
    h.send((7, 2))
    h.send((-7, 2))
    rt.flush()
    # Java: -7/2 == -3 (truncation), -7%2 == -1
    assert [e.data for e in got] == [(3, 1), (-3, -1)]


def test_chained_queries(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        from S[x > 0] select x * 10 as y insert into Mid;
        from Mid[y > 100] select y insert into Out;
    """)
    got = []
    rt.add_callback("Out", lambda evs: got.extend(evs))
    h = rt.input_handler("S")
    for x in [-1, 5, 11, 20]:
        h.send((x,))
    rt.flush()
    assert [e.data for e in got] == [(110,), (200,)]


def test_query_callback(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        @info(name='myq')
        from S[x > 1] select x insert into O;
    """)
    received = []
    rt.add_query_callback("myq", lambda ts, ins, outs: received.append((ins, outs)))
    h = rt.input_handler("S")
    h.send((0,))
    h.send((5,))
    rt.flush()
    assert len(received) == 1
    ins, outs = received[0]
    assert [e.data for e in ins] == [(5,)]
    assert outs is None


def test_ifthenelse_and_bool(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (p double);
        from S select ifThenElse(p > 10.0, p * 2.0, 0.0) as v insert into O;
    """)
    got = []
    rt.add_callback("O", lambda evs: got.extend(evs))
    h = rt.input_handler("S")
    h.send((5.0,))
    h.send((20.0,))
    rt.flush()
    assert [e.data for e in got] == [(0.0,), (40.0,)]


def test_event_timestamps_and_playback(mgr):
    rt = mgr.create_app_runtime("""
        @app:playback
        define stream S (x int);
        from S select eventTimestamp() as ts, x insert into O;
    """)
    got = []
    rt.add_callback("O", lambda evs: got.extend(evs))
    h = rt.input_handler("S")
    h.send((1,), timestamp=1000)
    h.send((2,), timestamp=2000)
    rt.flush()
    assert [e.data for e in got] == [(1000, 1), (2000, 2)]
    assert [e.timestamp for e in got] == [1000, 2000]


def test_large_batch_autoflush(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        from S[x % 7 == 0] select x insert into O;
    """)
    got = []
    rt.add_callback("O", lambda evs: got.extend(evs))
    h = rt.input_handler("S")
    n = 10_000
    for x in range(n):
        h.send((x,))
    rt.flush()
    assert [e.data[0] for e in got] == list(range(0, n, 7))


def test_validation_errors(mgr):
    with pytest.raises(Exception):
        mgr.create_app_runtime("""
            define stream S (x int);
            from S[nosuchattr > 1] select x insert into O;
        """)
    with pytest.raises(Exception):
        mgr.create_app_runtime("""
            define stream S (x int);
            from Unknown select x insert into O;
        """)


def test_constant_filter_and_constant_column():
    """Constant expressions have empty read-sets — the pruned-upload path
    must still evaluate them on device (review r5)."""
    from siddhi_tpu import SiddhiManager

    def run(app, sends):
        m = SiddhiManager()
        rt = m.create_app_runtime(app)
        rows = []
        rt.add_callback("Out", lambda evs: rows.extend(e.data for e in evs))
        rt.start()
        for r in sends:
            rt.input_handler("S").send(r)
        rt.flush()
        m.shutdown()
        return rows

    assert run("define stream S (x int);\n"
               "from S[1 < 0] select * insert into Out;", [(1,), (2,)]) == []
    assert run("define stream S (x int);\n"
               "from S select 42 as c insert into Out;", [(1,)]) == [(42,)]
    assert run("define stream S (a int, b int);\n"
               "from S select a, b having a > 0 insert into Out;",
               [(1, 5), (-1, 6)]) == [(1, 5)]
