"""Entry-point extension discovery (VERDICT r4 #8): a real on-disk
distribution (dist-info + entry_points.txt on sys.path) registers a
window via the `[siddhi_tpu.extensions]` group, and SiddhiQL resolves
`ns:win()`.  Mirrors core:util/SiddhiExtensionLoader.java:50-95."""
import sys
import textwrap

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.extension import (ENTRY_POINT_GROUP, ExtensionError,
                                  ExtensionMeta, Parameter, Example,
                                  discover_extensions, meta_for)


def _make_dist(tmp_path, name, ep_name, target, register_src):
    """A minimal path-based distribution importlib.metadata discovers."""
    pkg = tmp_path / name
    pkg.mkdir()
    (pkg / "__init__.py").write_text(register_src)
    di = tmp_path / f"{name}-1.0.dist-info"
    di.mkdir()
    (di / "METADATA").write_text(f"Metadata-Version: 2.1\nName: {name}\n"
                                 f"Version: 1.0\n")
    (di / "entry_points.txt").write_text(
        f"[{ENTRY_POINT_GROUP}]\n{ep_name} = {target}\n")
    return tmp_path


REGISTER_SRC = textwrap.dedent('''
    def register():
        from siddhi_tpu.extension import ExtensionMeta, Parameter, Example
        from siddhi_tpu.interp.engine import register_window_type
        from siddhi_tpu.interp import windows as W

        def build(args, ctx, schema):
            n = int(args[0].value)
            return W.LengthWindow(n)

        register_window_type(
            "keepLast", build, namespace="unit",
            meta=ExtensionMeta(
                name="keepLast", namespace="unit",
                description="sliding window keeping the last n events",
                parameters=(Parameter("n", ("int",), "window size"),),
                examples=(Example("from S#unit:keepLast(3) select *",
                                  "keeps 3 events"),)))
''')


def test_entry_point_window_resolves_in_siddhiql(tmp_path):
    _make_dist(tmp_path, "sidx_unit", "unit_ext", "sidx_unit:register",
               REGISTER_SRC)
    sys.path.insert(0, str(tmp_path))
    try:
        loaded = discover_extensions(force=True)
        assert "unit_ext" in loaded
        assert meta_for("window", "keepLast", "unit") is not None

        m = SiddhiManager()
        rt = m.create_app_runtime(
            "define stream S (x int);\n"
            "from S#window.unit:keepLast(2) select sum(x) as s insert into Out;\n")
        rows = []
        rt.add_callback("Out", lambda evs: rows.extend(e.data for e in evs))
        rt.start()
        h = rt.input_handler("S")
        for v in (1, 2, 3):
            h.send((v,))
        rt.flush()
        m.shutdown()
        assert rows == [(1,), (3,), (5,)]
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("sidx_unit", None)


def test_namespace_collision_enforced(tmp_path):
    """A namespace:name registered twice WITHIN one scan collides (the
    namespace is distinct from other tests so the collision exercised is
    the in-scan double registration, not leftover registry state)."""
    src = textwrap.dedent('''
    def register_dup():
        from siddhi_tpu.extension import ExtensionMeta, Parameter, Example
        from siddhi_tpu.interp.engine import register_window_type
        from siddhi_tpu.interp import windows as W
        meta = ExtensionMeta(
            name="dupWin", namespace="dupns",
            description="window registered twice",
            parameters=(Parameter("n", ("int",), "size"),),
            examples=(Example("#window.dupns:dupWin(1)", "dup"),))
        for _ in range(2):
            register_window_type(
                "dupWin",
                lambda args, ctx, schema: W.LengthWindow(1),
                namespace="dupns", meta=meta)
    ''')
    _make_dist(tmp_path, "sidx_dup", "dup_ext", "sidx_dup:register_dup",
               src)
    sys.path.insert(0, str(tmp_path))
    try:
        with pytest.raises(ExtensionError, match="duplicate"):
            discover_extensions(force=True)
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("sidx_dup", None)


def test_non_callable_entry_point_rejected(tmp_path):
    _make_dist(tmp_path, "sidx_bad", "bad_ext", "sidx_bad:NOT_CALLABLE",
               "NOT_CALLABLE = 42\n")
    sys.path.insert(0, str(tmp_path))
    try:
        with pytest.raises(ExtensionError, match="callable"):
            discover_extensions(force=True)
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("sidx_bad", None)


def test_discovery_runs_once():
    discover_extensions(force=True)
    assert discover_extensions() == []      # second call: no-op
