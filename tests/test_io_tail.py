"""Round-4 parity tail: @payload templating + text mappers (reference:
core:util/transport/TemplateBuilder.java, siddhi-map-text), broker
isolation, HA Source/SinkHandler SPI, @app:async knobs, and the fluent
programmatic query API (reference: SiddhiApp.java:72-198)."""
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.io import InMemoryBroker
from siddhi_tpu.core.planner import PlanError


def _collect(topic, broker=InMemoryBroker):
    got = []
    broker.subscribe(topic, got.append)
    return got


def test_payload_template_sink():
    app = """
    @sink(type='inMemory', topic='t1',
          @map(type='text', @payload('{{symbol}} went to {{price}}')))
    define stream S (symbol string, price double);
    """
    got = _collect("t1")
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    rt.start()
    rt.input_handler("S").send(("WSO2", 55.5))
    rt.flush()
    m.shutdown()
    assert got == ["WSO2 went to 55.5"]


def test_payload_template_unknown_attr_raises():
    app = """
    @sink(type='inMemory', topic='t2',
          @map(type='text', @payload('{{nope}}')))
    define stream S (symbol string);
    """
    with pytest.raises(PlanError, match="unknown attribute 'nope'"):
        SiddhiManager().create_app_runtime(app)


def test_text_sink_default_format():
    app = """
    @sink(type='inMemory', topic='t3', @map(type='text'))
    define stream S (symbol string, price double, volume int);
    """
    got = _collect("t3")
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    rt.start()
    rt.input_handler("S").send(("WSO2", 55.5, 10))
    rt.flush()
    m.shutdown()
    assert got == ['symbol:"WSO2",\nprice:55.5,\nvolume:10']


def test_text_source_parses_default_format():
    app = """
    @source(type='inMemory', topic='t4', @map(type='text'))
    define stream S (symbol string, price double, volume int);
    @info(name='q') from S select * insert into Out;
    """
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(tuple(e.data) for e in evs))
    rt.start()
    InMemoryBroker.publish("t4", 'symbol:"IBM",\nprice:75.25,\nvolume:42')
    m.shutdown()
    assert rows == [("IBM", 75.25, 42)]


def test_text_roundtrip_sink_to_source():
    """Parity loop: text sink output feeds a text source unchanged."""
    app = """
    @sink(type='inMemory', topic='loop', @map(type='text'))
    define stream A (symbol string, price double, volume int);
    @source(type='inMemory', topic='loop', @map(type='text'))
    define stream B (symbol string, price double, volume int);
    @info(name='q') from B select * insert into Out;
    """
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(tuple(e.data) for e in evs))
    rt.start()
    rt.input_handler("A").send(("X", 1.5, 3))
    rt.flush()
    m.shutdown()
    assert rows == [("X", 1.5, 3)]


def test_isolated_brokers_do_not_cross_deliver():
    app_sink = ("@sink(type='inMemory', topic='shared') "
                "define stream S (x int);")
    app_src = ("@source(type='inMemory', topic='shared') "
               "define stream R (x int);\n"
               "@info(name='q') from R select x insert into Out;")
    m1 = SiddhiManager(isolated_broker=True)
    m2 = SiddhiManager(isolated_broker=True)
    rt1 = m1.create_app_runtime(app_sink)
    rt2 = m2.create_app_runtime(app_src)
    rows = []
    rt2.add_callback("Out", lambda evs: rows.extend(e.data for e in evs))
    rt1.start()
    rt2.start()
    rt1.input_handler("S").send((1,))
    rt1.flush()
    assert rows == []           # different managers: no cross-talk
    # same manager's broker delivers
    m1.broker.subscribe("shared", lambda msg: rows.append(("raw", msg)))
    rt1.input_handler("S").send((2,))
    rt1.flush()
    assert rows == [("raw", (2,))]
    m1.shutdown()
    m2.shutdown()


def test_source_sink_handlers_intercept():
    from siddhi_tpu.core.io import SinkHandler, SourceHandler

    class DropOdd(SourceHandler):
        def on_rows(self, rows):
            return [(ts, r) for ts, r in rows if r[0] % 2 == 0]

    class Tag(SinkHandler):
        def on_events(self, events):
            return events       # passive observer
    seen = []

    class Spy(Tag):
        def on_events(self, events):
            seen.extend(e.data for e in events)
            return events

    m = SiddhiManager()
    m.set_source_handler_factory(DropOdd)
    m.set_sink_handler_factory(Spy)
    app = """
    @source(type='callback')
    @sink(type='inMemory', topic='h1')
    define stream S (x int);
    """
    got = _collect("h1")
    rt = m.create_app_runtime(app)
    rt.start()
    src = rt.sources_for("S")[0]
    assert src.handler is not None
    src.deliver([(1,), (2,), (3,), (4,)])
    m.shutdown()
    assert got == [(2,), (4,)]          # odd rows swallowed by the handler
    assert seen == [(2,), (4,)]         # sink handler observed deliveries


def test_async_knobs_parse_and_run():
    app = """
    @app:async(workers='2', batch.size.max='4', buffer.size='16')
    define stream S (x int);
    @info(name='q') from S select x insert into Out;
    """
    with pytest.warns(RuntimeWarning, match="cross-batch ordering"):
        m = SiddhiManager()
        rt = m.create_app_runtime(app)
    assert rt._async_workers == 2
    assert rt.batch_capacity == 4
    assert rt._async_buffer == 16
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(e.data[0] for e in evs))
    rt.start()
    h = rt.input_handler("S")
    for i in range(40):
        h.send((i,))
    rt.flush()
    m.shutdown()
    assert sorted(rows) == list(range(40))


def test_fluent_api_builds_running_app():
    from siddhi_tpu.api import Query, SiddhiAppBuilder, col

    app = (SiddhiAppBuilder("fluent-demo")
           .stream("S", symbol=str, price=float, volume=int)
           .query(Query("q1").from_stream("S")
                  .where(col("price") > 100)
                  .select(symbol=col("symbol"),
                          doubled=col("price") * 2)
                  .insert_into("Out"))
           .build())
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(tuple(e.data) for e in evs))
    rt.start()
    h = rt.input_handler("S")
    h.send(("A", 150.0, 1))
    h.send(("B", 50.0, 1))
    rt.flush()
    m.shutdown()
    assert rows == [("A", 300.0)]
    assert app.name == "fluent-demo"


def test_fluent_api_window_aggregation():
    from siddhi_tpu.api import Query, SiddhiAppBuilder, col

    app = (SiddhiAppBuilder("fluent-agg")
           .stream("S", sym=str, p=float)
           .query(Query("q").from_stream("S")
                  .window("length", 3)
                  .select(sym=col("sym"), total=col("p").sum())
                  .group_by("sym")
                  .insert_into("Out"))
           .build())
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(tuple(e.data) for e in evs))
    rt.start()
    h = rt.input_handler("S")
    for r in [("A", 1.0), ("A", 2.0), ("B", 5.0), ("A", 4.0)]:
        h.send(r)
    rt.flush()
    m.shutdown()
    assert rows[-1] == ("A", 6.0)       # window holds A:2, B:5, A:4
