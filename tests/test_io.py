"""Sources/sinks/mappers + InMemoryBroker + @OnError fault streams.

Reference test surface: modules/siddhi-core/src/test/java/org/wso2/siddhi/
core/stream/ (InMemorySourceTestCase, InMemorySinkTestCase), managment/
FaultStreamTestCase."""
import pytest

from siddhi_tpu import InMemoryBroker, SiddhiManager, register_source_type
from siddhi_tpu.core.io import Source


@pytest.fixture
def mgr():
    InMemoryBroker.reset()
    m = SiddhiManager()
    yield m
    m.shutdown()
    InMemoryBroker.reset()


def collect(rt, sid):
    out = []
    rt.add_callback(sid, lambda evs: out.extend(e.data for e in evs))
    return out


def test_inmemory_source(mgr):
    rt = mgr.create_app_runtime("""
        @source(type='inMemory', topic='stocks')
        define stream S (sym string, price double);
        from S[price > 10] select sym insert into O;
    """)
    out = collect(rt, "O")
    rt.start()
    InMemoryBroker.publish("stocks", ("A", 5.0))
    InMemoryBroker.publish("stocks", ("B", 20.0))
    assert out == [("B",)]
    rt.shutdown()
    # disconnected after shutdown: no more delivery
    InMemoryBroker.publish("stocks", ("C", 30.0))
    assert out == [("B",)]


def test_inmemory_sink(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        @sink(type='inMemory', topic='out')
        define stream O (x int);
        from S select x insert into O;
    """)
    got = []
    InMemoryBroker.subscribe("out", got.append)
    rt.start()
    rt.input_handler("S").send([(1,), (2,)])
    rt.flush()
    assert got == [(1,), (2,)]


def test_json_mappers_roundtrip(mgr):
    rt = mgr.create_app_runtime("""
        @source(type='inMemory', topic='in', @map(type='json'))
        define stream S (sym string, price double);
        @sink(type='inMemory', topic='out', @map(type='json'))
        define stream O (sym string, price double);
        from S select sym, price insert into O;
    """)
    got = []
    InMemoryBroker.subscribe("out", got.append)
    rt.start()
    InMemoryBroker.publish("in", '{"event": {"sym": "A", "price": 1.5}}')
    assert got == ['{"event": {"sym": "A", "price": 1.5}}']


def test_custom_source_type(mgr):
    class ListSource(Source):
        instances = []

        def connect(self):
            ListSource.instances.append(self)

    register_source_type("list", ListSource)
    rt = mgr.create_app_runtime("""
        @source(type='list')
        define stream S (x int);
        from S select x insert into O;
    """)
    out = collect(rt, "O")
    rt.start()
    ListSource.instances[-1].deliver([(1,), (2,)])
    assert out == [(1,), (2,)]


def test_on_error_fault_stream(mgr):
    from siddhi_tpu.interp.expr import register_py_function

    def _boom(args):
        f, t = args[0]
        def fn(env):
            v = f(env)
            if v == 0:
                raise ValueError("boom")
            return v
        return fn, t
    register_py_function("boom", _boom, "test")

    rt = mgr.create_app_runtime("""
        @OnError(action='stream')
        define stream S (x int, y int);
        from S select x, test:boom(y) as q insert into O;
        from !S select x, _error insert into F;
    """)
    ok, faults = collect(rt, "O"), collect(rt, "F")
    h = rt.input_handler("S")
    h.send((10, 2))
    rt.flush()
    # a processing exception routes the batch to !S
    h.send((11, 0))
    rt.flush()
    assert ok == [(10, 2)]
    assert len(faults) == 1 and faults[0][0] == 11
    assert "boom" in faults[0][1]


def test_fault_without_onerror_raises(mgr):
    with pytest.raises(Exception):
        mgr.create_app_runtime("""
            define stream S (x int);
            from !S select x insert into O;
        """)


def test_source_mapper_error_routes_to_fault(mgr):
    rt = mgr.create_app_runtime("""
        @OnError(action='stream')
        @source(type='inMemory', topic='t', @map(type='json'))
        define stream S (x int);
        from !S select _error insert into F;
    """)
    faults = collect(rt, "F")
    rt.start()
    InMemoryBroker.publish("t", "{not json")
    assert len(faults) == 1 and "map error" in faults[0][0]
