"""Wire-served store queries (QUERY/RESULT frames): round-trips
byte-identical to in-process `runtime.query()` including string
columns, WS parity, query-only connections, token-correlated errors,
and the feed-gate regression — store queries racing a paced ingest
thread always observe fully-merged bucket state."""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.net import NetClientError, TcpFrameClient, WsFrameClient
from siddhi_tpu.service import SiddhiService

TS0 = 1_700_000_000_000

AGG_BODY = (
    "define stream Trades (sym string, price double, ts long);\n"
    "define aggregation TradeAgg\n"
    "from Trades\n"
    "select sym, sum(price) as total, avg(price) as mean, count() as n\n"
    "group by sym\n"
    "aggregate by ts every sec, min;\n")

QUERY = (f"from TradeAgg within {TS0 - 60_000}L, {TS0 + 600_000}L "
         f"per 'sec' select sym, total, mean, n")


def make_batches(n_batches=5, batch=48, seed=11, nsym=6):
    rng = np.random.default_rng(seed)
    out = []
    for k in range(n_batches):
        ts = TS0 + k * 2_500 + np.sort(rng.integers(0, 2_500, batch))
        out.append((
            {"sym": np.array([f"SYM{i}" for i in
                              rng.integers(0, nsym, batch)]),
             "price": rng.uniform(10, 500, batch),
             "ts": ts.astype(np.int64)},
            ts.astype(np.int64)))
    return out


@pytest.fixture()
def wired():
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@source(type='tcp', port='0')\n" + AGG_BODY)
    rt.start()
    yield rt
    mgr.shutdown()


def _client(rt, cls=TcpFrameClient, stream="Trades"):
    cols = cls.cols_of_schema(rt.schemas[stream]) if stream else None
    return cls("127.0.0.1", rt.sources[0].port, stream, cols)


def test_wire_query_matches_runtime_query(wired):
    rt = wired
    cli = _client(rt)
    for c, ts in make_batches():
        cli.send_batch(c, ts)
    cli.barrier()
    host = rt.query(QUERY)
    wire = cli.query(QUERY)
    cli.close()
    assert len(wire) > 0
    # byte-identical: f64 totals compare with ==, string group keys
    # resolved through the egress dictionary, counts as ints
    assert sorted(wire) == sorted(host)


def test_ws_query_matches_runtime_query():
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@source(type='ws', port='0')\n" + AGG_BODY)
    rt.start()
    cli = _client(rt, cls=WsFrameClient)
    for c, ts in make_batches(n_batches=3):
        cli.send_batch(c, ts)
    cli.barrier()
    wire = cli.query(QUERY)
    host = rt.query(QUERY)
    cli.close()
    mgr.shutdown()
    assert len(wire) > 0 and sorted(wire) == sorted(host)


def test_string_dictionary_delta_across_queries(wired):
    """The per-connection egress dictionary ships each string once;
    later RESULTs reference earlier codes and only delta new ones."""
    rt = wired
    cli = _client(rt)
    batches = make_batches(n_batches=4, nsym=3)
    cli.send_batch(*batches[0])
    cli.barrier()
    assert sorted(cli.query(QUERY)) == sorted(rt.query(QUERY))
    # new symbols appear between queries -> second RESULT needs a
    # STRINGS delta on top of the already-shipped codes
    for c, ts in make_batches(n_batches=2, seed=99, nsym=9):
        cli.send_batch(c, ts)
    cli.barrier()
    assert sorted(cli.query(QUERY)) == sorted(rt.query(QUERY))
    # and a third query with nothing new ships no fresh strings but
    # still resolves every code
    assert sorted(cli.query(QUERY)) == sorted(rt.query(QUERY))
    cli.close()


def test_query_error_correlates_token_and_connection_survives(wired):
    rt = wired
    cli = _client(rt)
    cli.send_batch(*make_batches(n_batches=1)[0])
    cli.barrier()
    with pytest.raises(NetClientError, match="not a table"):
        cli.query("from NoSuchAgg select x")
    # the error rode a RESULT frame for this token only -- the
    # connection (and its ingest plane) is still healthy
    cli.send_batch(*make_batches(n_batches=1, seed=5)[0])
    cli.barrier()
    assert sorted(cli.query(QUERY)) == sorted(rt.query(QUERY))
    cli.close()


def test_named_app_query_needs_service_resolver(wired):
    """A bare @source server has no app registry: named-app store
    queries are refused with a pointed error, HELLO-bound ones work."""
    rt = wired
    cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, app="QApp")
    with pytest.raises(NetClientError, match="named-app store queries"):
        cli.query(QUERY)
    cli.close()


def test_store_query_under_paced_ingest_feed_gate(wired):
    """Regression: store queries used to race the scheduler drain and
    could observe half-merged bucket state.  Routed under the runtime
    feed gate, every RESULT reflects a batch boundary: sum(price) with
    price==1.0 must equal count() in every row of every probe."""
    rt = wired
    n_batches, batch = 12, 64
    stop = threading.Event()
    err = []

    def feed():
        fcli = _client(rt)
        try:
            for k in range(n_batches):
                ts = TS0 + np.arange(k * batch, (k + 1) * batch,
                                     dtype=np.int64)
                fcli.send_batch(
                    {"sym": np.array([f"S{i % 7}" for i in range(batch)]),
                     "price": np.ones(batch), "ts": ts}, ts)
                time.sleep(0.005)
            fcli.barrier()
        except Exception as e:        # pragma: no cover - surfaced below
            err.append(e)
        finally:
            stop.set()
            fcli.close()

    qcli = _client(rt)
    t = threading.Thread(target=feed)
    t.start()
    probes = 0
    seen = 0
    try:
        while not stop.is_set() or probes == 0:
            rows = qcli.query(QUERY)
            probes += 1
            total_n = 0
            for _ts, (sym, total, mean, n) in rows:
                assert total == float(n), (sym, total, n)
                assert n == 0 or mean == 1.0
                total_n += n
            assert total_n >= seen, "store view went backwards"
            seen = total_n
            time.sleep(0.002)
    finally:
        t.join()
        qcli.close()
    assert not err, err
    assert probes >= 2
    # after the barrier the final view is complete
    final = sum(n for _ts, (_s, _t, _m, n) in rt.query(QUERY))
    assert final == n_batches * batch
    assert (rt.stats.report()["aggregation"]["store_query"]["batches"]
            >= probes)


def test_rest_query_parity_with_wire():
    svc = SiddhiService(port=0, net=True).start()
    try:
        body = ("@app:name('QDemo')\n" + AGG_BODY).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{svc.port}/siddhi/artifact/deploy",
            data=body, method="POST")
        with urllib.request.urlopen(req) as r:
            assert json.loads(r.read())["status"] == "deployed"
        rt = svc.runtimes["QDemo"]
        h = rt.input_handler("Trades")
        for c, ts in make_batches(n_batches=3):
            h.send_batch(c, ts)
        rt.flush()
        host = rt.query(QUERY)
        # wire path: query-only connection resolved by app name
        cli = TcpFrameClient("127.0.0.1", svc.net_port, app="QDemo")
        wire = cli.query(QUERY)
        cli.close()
        assert len(wire) > 0 and sorted(wire) == sorted(host)
        # REST path: same rows, JSON-shaped
        req = urllib.request.Request(
            f"http://127.0.0.1:{svc.port}/siddhi/artifact/query",
            data=json.dumps({"app": "QDemo", "query": QUERY}).encode(),
            method="POST")
        with urllib.request.urlopen(req) as r:
            rest = json.loads(r.read())["rows"]
        assert sorted(map(tuple, ((ts, tuple(row)) for ts, row in rest))) \
            == sorted(host)
    finally:
        svc.stop()
