"""Continuous device-time attribution (core/profiler.py, ISSUE 17):
the phase profiler must be output-invariant across every plan family,
publish shares that sum to exactly 1.0 with >= 0.9 coverage of the
dispatch wall, honor the kernel-round duty cycle, serve
/siddhi/artifact/profile, render grammar-valid Prometheus phase
series, fire the host-share breach trigger through the tracing
registry, and the perfcheck sentinel must trip on a seeded 2x
host-dispatch regression while passing a fresh baseline."""
import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.profiler import (HOST_PHASES, PHASES, PhaseProfiler,
                                      fold_roofline)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STOCK = "define stream S (sym string, p double, v int);\n"

FAMILIES = {
    "filter": "@info(name='q') from S[p > 10] select sym, p "
              "insert into Out;\n",
    "window": "@info(name='q') from S#window.length(64) select sym, "
              "sum(p) as s insert into Out;\n",
    "pattern": "@info(name='q') from every e1=S[p > 10] -> e2=S[p > e1.p] "
               "select e1.sym as s1, e2.p as p2 insert into Out;\n",
    "join": "define stream T (sym string, q double);\n"
            "@info(name='q') from S#window.length(32) as a join "
            "T#window.length(32) as b on a.sym == b.sym "
            "select a.sym as sym, a.p as p, b.q as q insert into Out;\n",
}


def _cols(n, seed=0):
    r = np.random.default_rng(seed)
    return {"sym": np.array([f"K{i % 4}" for i in range(n)]),
            "p": np.round(r.uniform(5.0, 20.0, n), 2),
            "v": r.integers(1, 100, n).astype(np.int32)}


# devicePatterns defaults to 'auto', which routes unpartitioned patterns
# to the host matcher — force the device NFA so the pattern family
# actually exercises kernel-round accounting
PREFER = "@app:devicePatterns('prefer')\n"


def _run_family(head, family, batches=6, n=64):
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(head + PREFER + STOCK + FAMILIES[family])
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(repr(e) for e in evs))
    rt.start()
    h = rt.input_handler("S")
    hj = rt.input_handler("T") if family == "join" else None
    for k in range(batches):
        h.send_batch(_cols(n, seed=k), np.arange(n) + n * k)
        if hj is not None:
            c = _cols(n, seed=100 + k)
            hj.send_batch({"sym": c["sym"], "q": c["p"]},
                          np.arange(n) + n * k)
        rt.flush()
    prof = rt.profiler.metrics() if rt.profiler is not None else None
    mgr.shutdown()
    return rows, prof


# ---------------------------------------------------------------------------
# tentpole: output invariance + attribution invariants, all plan families
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_profiler_output_invariant_per_family(family):
    """off / all / sample=2 must be byte-identical: observation must
    never change what the engine computes."""
    base, _ = _run_family("@app:profile('off')\n", family)
    assert base, f"{family}: no output rows at all"
    for head in ("@app:profile('all')\n", "@app:profile('sample=2')\n"):
        got, prof = _run_family(head, family)
        assert got == base, f"{family} {head.strip()}: outputs diverged"
        assert prof is not None and prof["plans"]


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_shares_sum_to_one_and_coverage(family):
    """Per-plan and aggregate shares sum to exactly 1.0 (normalized
    over the corrected total) and phase attribution covers >= 0.9 of
    the dispatch wall — the ISSUE 17 acceptance bar."""
    _, prof = _run_family("@app:profile('all')\n", family)
    for name, pv in prof["plans"].items():
        s = sum(pv["shares"].values())
        assert abs(s - 1.0) < 5e-4, (name, pv["shares"])
        assert set(pv["shares"]) == set(PHASES)
        host = sum(pv["shares"][k] for k in HOST_PHASES)
        assert abs(host - pv["host_dispatch_share"]) < 1e-3
    agg = prof["aggregate"]
    assert abs(sum(agg["shares"].values()) - 1.0) < 5e-4
    assert agg["coverage"] >= 0.9, agg
    assert agg["rounds"] > 0 and agg["events"] > 0


def test_duty_cycle_counts_kernel_rounds():
    """sample=N probes ~1 in N KERNEL-carrying rounds: collect polls
    and scheduler pumps open kernel-less rounds and must not consume
    the cycle (the bug that zeroed kernel shares on the TCP path)."""
    _, prof = _run_family("@app:profile('sample=3')\n", "pattern",
                          batches=12)
    agg = prof["aggregate"]
    kr, sr = agg["kernel_rounds"], agg["sampled_rounds"]
    assert kr >= 6, agg
    # ceil(kr / 3) sampled, +-1 for the counter being shared app-wide
    want = -(-kr // 3)
    assert abs(sr - want) <= 1, (kr, sr, want)
    # the probe actually measured device time on those rounds
    assert agg["phases_s"]["kernel_compute"] > 0.0


def test_all_mode_does_not_extrapolate():
    """mode='all' blocks every kernel round: sampled == kernel rounds,
    so the extrapolation factor must stay 1 (kernel seconds reported
    exactly as measured, not scaled by kernel-less round wall)."""
    _, prof = _run_family("@app:profile('all')\n", "pattern")
    for pv in prof["plans"].values():
        if pv["kernel_rounds"]:
            assert pv["sampled_rounds"] == pv["kernel_rounds"], pv


def test_statistics_report_always_carries_profile():
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:profile('all')\n" + STOCK + FAMILIES["filter"])
    rt.start()
    h = rt.input_handler("S")
    h.send_batch(_cols(32), np.arange(32))
    rt.flush()
    rep = rt.statistics()
    assert rep["profile"]["mode"] == "all"
    assert rep["profile"]["plans"]
    prof = rt.profile()
    assert "windows" in prof
    # the roofline fold names the plan family for device plans
    fams = [pv.get("roofline", {}).get("plan_family")
            for name, pv in prof["plans"].items()
            if not name.startswith("_")]
    assert fams
    mgr.shutdown()


def test_profile_off_is_absent():
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:profile('off')\n" + STOCK + FAMILIES["filter"])
    rt.start()
    assert rt.profiler is None
    assert rt.profile() == {"mode": "off"}
    assert "profile" not in rt.statistics()
    mgr.shutdown()


def test_unknown_mode_rejected():
    from siddhi_tpu.core.planner import PlanError
    with pytest.raises(PlanError):
        SiddhiManager().create_app_runtime(
            "@app:profile('sometimes')\n" + STOCK + FAMILIES["filter"])


# ---------------------------------------------------------------------------
# breach trigger through the tracing registry
# ---------------------------------------------------------------------------

def test_host_share_breach_fires_tracing_trigger(tmp_path):
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        "@app:profile(window='0.05')\n@app:hostShareAlert('0.01')\n"
        f"@app:trace('all', export='{tmp_path}')\n"
        + STOCK + FAMILIES["filter"])
    rt.start()
    h = rt.input_handler("S")
    import time
    deadline = time.time() + 10.0
    k = 0
    while time.time() < deadline:
        h.send_batch(_cols(64, seed=k), np.arange(64) + 64 * k)
        rt.flush()
        k += 1
        if rt.profiler.breaches:
            break
        time.sleep(0.02)
    assert rt.profiler.breaches > 0, "window never breached a 1% alert"
    tm = rt.tracing.metrics()
    deadline = time.time() + 5.0
    while time.time() < deadline and not tm["triggers"].get(
            "host_share_breach"):
        time.sleep(0.05)
        tm = rt.tracing.metrics()
    assert tm["triggers"].get("host_share_breach", 0) > 0, tm
    mgr.shutdown()


# ---------------------------------------------------------------------------
# service endpoint + Prometheus grammar
# ---------------------------------------------------------------------------

def test_service_profile_endpoint_and_prometheus():
    from siddhi_tpu.service import SiddhiService
    from tests.test_tracing import assert_valid_exposition
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        app = ("@app:name('ProfEp')\n@app:profile('all')\n"
               + PREFER + STOCK + FAMILIES["pattern"])
        req = urllib.request.Request(f"{base}/siddhi/artifact/deploy",
                                     data=app.encode(), method="POST")
        urllib.request.urlopen(req).read()
        rt = svc.runtimes["ProfEp"]
        h = rt.input_handler("S")
        for k in range(4):
            h.send_batch(_cols(64, seed=k), np.arange(64) + 64 * k)
        rt.flush()
        with urllib.request.urlopen(
                f"{base}/siddhi/artifact/profile?siddhiApp=ProfEp") as r:
            assert r.status == 200
            prof = json.loads(r.read())["apps"]["ProfEp"]
        assert prof["mode"] == "all" and prof["plans"]
        for pv in prof["plans"].values():
            assert abs(sum(pv["shares"].values()) - 1.0) < 5e-4
        # windowed slice: ?window=0 -> no ring entries, still 200
        with urllib.request.urlopen(
                f"{base}/siddhi/artifact/profile?siddhiApp=ProfEp"
                f"&window=0") as r:
            assert json.loads(r.read())["apps"]["ProfEp"]["windows"] == []
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{base}/siddhi/artifact/profile?siddhiApp=NoSuchApp")
        assert ei.value.code == 404
        with urllib.request.urlopen(f"{base}/metrics") as r:
            text = r.read().decode()
        assert_valid_exposition(text)
        phase_lines = [ln for ln in text.splitlines()
                       if ln.startswith("siddhi_tpu_phase_seconds_total{")]
        assert phase_lines
        assert any('phase="kernel_compute"' in ln for ln in phase_lines)
        assert any(ln.startswith("siddhi_tpu_host_dispatch_share{")
                   for ln in text.splitlines())
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# perfcheck sentinel
# ---------------------------------------------------------------------------

def _fake_report(host3=0.2, host4=0.25):
    return {
        "metric": "stage_breakdown_config3", "eps": 400000,
        "coverage": 0.95, "kernel_share": round(1 - host3, 4),
        "host_dispatch_share": host3,
        "profile": {"coverage": 0.98,
                    "shares": {"h2d_upload": 0.1, "kernel_compute": 0.55,
                               "d2h_materialize": 0.1,
                               "host_pack_unpack": 0.1,
                               "python_dispatch": 0.15,
                               "sink_egress": 0.0},
                    "host_dispatch_share": host3,
                    "plans": {"q": {"kernel_eps": 700000.0}}},
        "config4": {"eps": 150000, "host_dispatch_share": host4,
                    "profile": {"coverage": 0.97}},
        "profile_overhead": {"sampled_32_overhead_pct": 1.0, "pass": True},
        "harness": {"config_hash": "deadbeef0123", "git_rev": "abc1234"},
    }


def _perfcheck(tmp_path, args, report):
    inp = tmp_path / "report.json"
    inp.write_text(json.dumps(report) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "perfcheck.py"),
         "--input", str(inp), *args],
        capture_output=True, text=True, timeout=120)
    last = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "{}"
    return r.returncode, json.loads(last), r.stderr


def test_perfcheck_fresh_baseline_passes_and_x2_trips(tmp_path):
    base_path = tmp_path / "perf_baseline.json"
    rep = _fake_report()
    rc, out, err = _perfcheck(
        tmp_path, ["--write-baseline", str(base_path)], rep)
    assert rc == 0 and out["pass"], (out, err)
    assert base_path.exists()
    # fresh report vs its own baseline: pass, no failures
    rc, out, _ = _perfcheck(tmp_path, ["--baseline", str(base_path)], rep)
    assert rc == 0 and out["pass"] and not out["failures"], out
    # seeded 2x host-dispatch-seconds regression: MUST exit 1
    rc, out, _ = _perfcheck(
        tmp_path, ["--baseline", str(base_path),
                   "--inject-host-share-x2"], rep)
    assert rc == 1 and not out["pass"], out
    assert any("host_dispatch_share" in f for f in out["failures"]), out


def test_perfcheck_stale_config_hash_passes_with_note(tmp_path):
    base_path = tmp_path / "perf_baseline.json"
    _perfcheck(tmp_path, ["--write-baseline", str(base_path)],
               _fake_report())
    moved = _fake_report(host3=0.6, host4=0.7)
    moved["harness"]["config_hash"] = "0123deadbeef"
    rc, out, _ = _perfcheck(tmp_path, ["--baseline", str(base_path)], moved)
    assert rc == 0 and out.get("stale_baseline"), out


def test_checked_in_baseline_parses():
    """The committed baseline must stay loadable with the fields the
    sentinel and the live roofline fold read."""
    path = os.path.join(ROOT, "scripts", "perf_baseline.json")
    with open(path) as f:
        base = json.load(f)
    assert base["schema"] == 1
    for cfg in ("config3", "config4"):
        assert base["metrics"][cfg]["host_dispatch_share"] is not None
    assert "native_cpp_eps" in base
    assert base["harness"].get("config_hash")


def test_fold_roofline_reads_baseline(tmp_path, monkeypatch):
    """fold_roofline maps plan families onto the baseline's native
    eps column (via $SIDDHI_PERF_BASELINE)."""
    from siddhi_tpu.core import profiler as pmod
    bl = {"native_cpp_eps": {"3_sequence": 1_000_000.0,
                             "4_partitioned": 2_000_000.0}}
    p = tmp_path / "bl.json"
    p.write_text(json.dumps(bl))
    monkeypatch.setenv("SIDDHI_PERF_BASELINE", str(p))
    monkeypatch.setitem(pmod._roofline_cache, "loaded", False)
    monkeypatch.setitem(pmod._roofline_cache, "eps", {})

    class FakePlan:
        name, family = "q", "scan"
    rep = {"plans": {"q": {"kernel_eps": 500000.0,
                           "end_to_end_eps": 300000.0}}}
    fold_roofline(rep, [FakePlan()])
    roof = rep["plans"]["q"]["roofline"]
    assert roof["native_cpp_eps"] == 1_000_000.0
    assert roof["vs_native_cpp"] == 0.5
    # cache poisoning across tests: restore the unloaded state
    monkeypatch.setitem(pmod._roofline_cache, "loaded", False)
    monkeypatch.setitem(pmod._roofline_cache, "eps", {})


def test_profiler_spawns_no_threads():
    import threading
    before = {t.name for t in threading.enumerate()}
    _, prof = _run_family("@app:profile('all')\n", "filter", batches=2)
    assert prof["plans"]
    after = {t.name for t in threading.enumerate()} - before
    assert not any(n.startswith("siddhi-profile") for n in after), after
