"""Mesh sharding + async ingest tests (8 virtual CPU devices, conftest)."""
import numpy as np
import pytest

import jax

from siddhi_tpu import SiddhiManager

PART_APP = """
@app:deviceMesh('always')
@app:partitionCapacity(16)
define stream S (sym string, p double);
partition with (sym of S)
begin
  @info(name='q')
  from every e1=S[p > 100] -> e2=S[p > e1.p] within 10 sec
  select e1.p as p1, e2.p as p2 insert into M;
end;
"""


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def _feed(rt, sends):
    out = []
    rt.add_callback("M", lambda evs: out.extend(e.data for e in evs))
    h = rt.input_handler("S")
    rt.start()
    for sym, p, ts in sends:
        h.send((sym, p), timestamp=ts)
    rt.flush()
    return out


def _tape(n=300, keys=12, seed=2):
    rng = np.random.default_rng(seed)
    return [("K%d" % int(rng.integers(keys)),
             float(np.round(rng.uniform(90, 120) * 4) / 4), 1000 + i)
            for i in range(n)]


def test_mesh_sharded_state_and_results(mgr):
    from siddhi_tpu.core.pattern_plan import DevicePatternPlan
    assert len(jax.devices()) == 8, "conftest should give 8 virtual devices"
    sends = _tape()
    rt = mgr.create_app_runtime(PART_APP)
    plan = next(p for p in rt._plans if isinstance(p, DevicePatternPlan))
    assert plan.mesh is not None
    assert plan.P % 8 == 0
    # state leaves actually live sharded over all 8 devices
    occ = plan.state["occ"]
    assert len(occ.sharding.device_set) == 8
    dev_out = _feed(rt, sends)

    host = mgr.create_app_runtime(
        "@app:devicePatterns('never')\n" + PART_APP.replace(
            "@app:deviceMesh('always')\n", ""))
    host_out = _feed(host, sends)
    # per-key order is guaranteed; cross-key interleave is not
    assert sorted(dev_out) == sorted(host_out)
    assert len(dev_out) > 0
    # post-flush state is still sharded (no silent gather-to-one-device)
    assert len(plan.state["occ"].sharding.device_set) == 8


def test_mesh_snapshot_restore(mgr):
    from siddhi_tpu.core.pattern_plan import DevicePatternPlan
    sends = _tape(120)
    rt = mgr.create_app_runtime(PART_APP)
    out = _feed(rt, sends)
    snap = rt.snapshot()

    rt2 = mgr.create_app_runtime(PART_APP)
    out2 = []
    rt2.add_callback("M", lambda evs: out2.extend(e.data for e in evs))
    rt2.restore(snap)
    plan2 = next(p for p in rt2._plans if isinstance(p, DevicePatternPlan))
    assert len(plan2.state["occ"].sharding.device_set) == 8
    h = rt2.input_handler("S")
    h.send(("K1", 101.0), timestamp=5000)
    h.send(("K1", 102.0), timestamp=5001)
    rt2.flush()
    assert (101.0, 102.0) in out2


ASYNC_APP = """
@app:async('true')
define stream S (sym string, p double);
@info(name='q') from S[p > 100] select sym, p insert into Out;
"""


def test_async_ingest_equivalence(mgr):
    sends = _tape(5000)
    outs = []
    for app in (ASYNC_APP, ASYNC_APP.replace("@app:async('true')\n", "")):
        rt = mgr.create_app_runtime(app)
        got = []
        rt.add_callback("Out", lambda evs, g=got: g.extend(e.data for e in evs))
        rt.start()
        h = rt.input_handler("S")
        for sym, p, ts in sends:
            h.send((sym, p), timestamp=ts)
        rt.flush()          # async barrier: all callbacks delivered after
        outs.append(got)
        rt.shutdown()
    a, b = outs
    assert a == b and len(a) > 0


def test_async_worker_error_surfaces(mgr):
    """Failures on the ingest worker thread re-raise at the flush barrier."""
    rt = mgr.create_app_runtime(ASYNC_APP)
    rt.start()
    plan = rt._plans[0]

    def boom(*_a, **_k):
        raise RuntimeError("kaboom on worker")
    plan.process = boom
    h = rt.input_handler("S")
    h.send(("K", 101.0), timestamp=1000)
    with pytest.raises(RuntimeError, match="kaboom"):
        rt.flush()
    rt.shutdown()
