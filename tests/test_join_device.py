"""Device window-join kernel (VERDICT r4 #2) — differential vs the host
interp join (interp/joins.py), which mirrors the reference JoinProcessor
(core:query/input/stream/join/JoinProcessor.java:62-126)."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.join_device import DeviceJoinPlan

HEAD = ("define stream L (sym string, lp double, ln int);\n"
        "define stream R (sym string, rp double, rn int);\n")


def run(head, app, sends, flush_every=7):
    m = SiddhiManager()
    rt = m.create_app_runtime(head + app)
    kinds = [type(p).__name__ for p in rt._plans]
    rows = []
    rt.add_callback("O", lambda evs: rows.extend(
        (e.timestamp, e.data) for e in evs))
    rt.start()
    for i, (sid, row, ts) in enumerate(sends):
        rt.send(sid, row, timestamp=ts)
        if flush_every and i % flush_every == 0:
            rt.flush()
    rt.flush()
    m.shutdown()
    return kinds, rows


def both(app, sends, flush_every=7, device=True):
    k1, dev = run("", HEAD + app, sends, flush_every)
    if device:
        assert "DeviceJoinPlan" in k1, k1
    k2, host = run("@app:deviceJoins('never')\n", HEAD + app, sends,
                   flush_every)
    assert "InterpJoinQueryPlan" in k2
    assert len(dev) == len(host), (len(dev), len(host), dev[:4], host[:4])
    for d, h in zip(dev, host):
        assert d[0] == h[0], (d, h)
        for a, b in zip(d[1], h[1]):
            if isinstance(a, float) and isinstance(b, float):
                assert abs(a - b) <= 1e-3 + 1e-5 * abs(b), (d, h)
            else:
                assert a == b, (d, h)
    return dev


def mk_sends(n, keys=3, seed=0, both_streams=True):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        sid = "L" if (not both_streams or rng.random() < 0.5) else "R"
        row = (f"K{int(rng.integers(keys))}",
               float(rng.integers(1, 40)), int(rng.integers(0, 9)))
        out.append((sid, row, 1000 + i))
    return out


INNER = ("from L#window.length(5) as a join R#window.length(4) as b "
         "on a.sym == b.sym select a.sym as s, a.lp as lp, b.rp as rp "
         "insert into O;")


def test_inner_equality():
    assert both(INNER, mk_sends(80))


def test_residual_condition():
    app = ("from L#window.length(6) as a join R#window.length(6) as b "
           "on a.sym == b.sym and a.lp > b.rp "
           "select a.sym as s, a.lp as lp, b.rp as rp insert into O;")
    assert both(app, mk_sends(80, seed=1))


def test_non_equality_condition():
    """The dense grid needs no equality key at all."""
    app = ("from L#window.length(5) as a join R#window.length(5) as b "
           "on a.lp < b.rp select a.lp as x, b.rp as y insert into O;")
    assert both(app, mk_sends(60, seed=2))


def test_no_condition_cross_join():
    app = ("from L#window.length(3) as a join R#window.length(3) as b "
           "select a.lp as x, b.rp as y insert into O;")
    assert both(app, mk_sends(50, seed=3))


@pytest.mark.parametrize("jt", ["left outer join", "right outer join",
                                "full outer join"])
def test_outer_joins(jt):
    app = (f"from L#window.length(4) as a {jt} R#window.length(4) as b "
           f"on a.sym == b.sym "
           f"select a.sym as s, a.lp as lp, b.rp as rp insert into O;")
    out = both(app, mk_sends(70, keys=5, seed=4))
    assert any(None in r for _t, r in out), "outer rows must include nulls"


@pytest.mark.parametrize("uni", ["left", "right"])
def test_unidirectional(uni):
    sides = {"left": "L#window.length(4) as a unidirectional join "
                     "R#window.length(4) as b",
             "right": "L#window.length(4) as a join "
                      "R#window.length(4) as b unidirectional"}
    app = (f"from {sides[uni]} on a.sym == b.sym "
           f"select a.lp as x, b.rp as y insert into O;")
    assert both(app, mk_sends(60, seed=5))


def test_side_filters():
    app = ("from L[lp > 10]#window.length(4) as a join "
           "R[rp < 30]#window.length(4) as b on a.sym == b.sym "
           "select a.lp as x, b.rp as y insert into O;")
    assert both(app, mk_sends(80, seed=6))


def test_computed_outputs():
    app = ("from L#window.length(4) as a join R#window.length(4) as b "
           "on a.sym == b.sym "
           "select a.lp + b.rp as tot, a.lp * 2.0 as dl, "
           "a.ln + b.rn as cnt insert into O;")
    assert both(app, mk_sends(70, seed=7))


def test_computed_outputs_outer_misses():
    """Miss rows force host-closure evaluation of derived outputs."""
    app = ("from L#window.length(4) as a left outer join "
           "R#window.length(4) as b on a.sym == b.sym "
           "select a.lp + b.rp as tot, a.sym as s insert into O;")
    out = both(app, mk_sends(50, keys=6, seed=8))
    assert any(r[0] is None for _t, r in out)


def test_windowless_side():
    """A windowless side retains nothing: only the other side's window
    is probed."""
    app = ("from L as a join R#window.length(4) as b on a.sym == b.sym "
           "select a.lp as x, b.rp as y insert into O;")
    assert both(app, mk_sends(50, seed=9))


def test_self_join():
    app = ("define stream S (sym string, p double);\n"
           "from S#window.length(4) as a join S#window.length(3) as b "
           "on a.sym == b.sym and a.p > b.p "
           "select a.p as x, b.p as y insert into O;")
    rng = np.random.default_rng(10)
    sends = [("S", (f"K{int(rng.integers(2))}", float(rng.integers(1, 30))),
              1000 + i) for i in range(50)]
    k1, dev = run("", app, sends)
    assert "DeviceJoinPlan" in k1
    k2, host = run("@app:deviceJoins('never')\n", app, sends)
    assert dev == host and dev


def test_select_star():
    app = ("from L#window.length(3) as a join R#window.length(3) as b "
           "on a.sym == b.sym select * insert into O;")
    assert both(app, mk_sends(40, seed=11))


def test_per_event_flush_matches_batch_flush():
    """Window evolution inside one flush must equal per-event flushes."""
    app = INNER
    sends = mk_sends(60, seed=12)
    _k, fine = run("", HEAD + app, sends, flush_every=1)
    _k, coarse = run("", HEAD + app, sends, flush_every=0)
    assert fine == coarse


def test_fallback_shapes_stay_host():
    for app in (
            "from L#window.time(1 sec) as a join R#window.length(3) as b "
            "on a.sym == b.sym select a.lp as x insert into O;",
            "from L#window.length(3) as a join R#window.length(3) as b "
            "on a.sym == b.sym select max(a.lp) as m insert into O;"):
        m = SiddhiManager()
        rt = m.create_app_runtime(HEAD + app)
        assert not any(isinstance(p, DeviceJoinPlan) for p in rt._plans)
        m.shutdown()
    m = SiddhiManager()
    with pytest.raises(Exception, match="deviceJoins"):
        m.create_app_runtime(
            "@app:deviceJoins('always')\n" + HEAD +
            "from L#window.time(1 sec) as a join R#window.length(3) as b "
            "on a.sym == b.sym select a.lp as x insert into O;")
    m.shutdown()


def test_snapshot_restore():
    app = "@app:deviceJoins('auto')\n" + HEAD + INNER
    sends = mk_sends(40, seed=13)
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    rows = []
    rt.add_callback("O", lambda evs: rows.extend(tuple(e.data) for e in evs))
    rt.start()
    for sid, row, ts in sends[:20]:
        rt.send(sid, row, timestamp=ts)
    rt.flush()
    snap = rt.snapshot()
    m.shutdown()

    m2 = SiddhiManager()
    rt2 = m2.create_app_runtime(app)
    rows2 = []
    rt2.add_callback("O", lambda evs: rows2.extend(tuple(e.data)
                                                   for e in evs))
    rt2.start()
    rt2.restore(snap)
    for sid, row, ts in sends[20:]:
        rt2.send(sid, row, timestamp=ts)
    rt2.flush()
    m2.shutdown()

    # continuous run for comparison
    m3 = SiddhiManager()
    rt3 = m3.create_app_runtime(app)
    rows3 = []
    rt3.add_callback("O", lambda evs: rows3.extend(tuple(e.data)
                                                   for e in evs))
    rt3.start()
    for sid, row, ts in sends[:20]:
        rt3.send(sid, row, timestamp=ts)
    rt3.flush()
    for sid, row, ts in sends[20:]:
        rt3.send(sid, row, timestamp=ts)
    rt3.flush()
    m3.shutdown()
    assert rows + rows2 == rows3


@pytest.mark.parametrize("seed", range(5))
def test_fuzz(seed):
    shapes = [
        INNER,
        "from L#window.length(7) as a full outer join R#window.length(2) "
        "as b on a.sym == b.sym and a.ln != b.rn "
        "select a.sym as s, a.ln as x, b.rn as y insert into O;",
        "from L[ln > 2]#window.length(3) as a left outer join "
        "R#window.length(5) as b on a.sym == b.sym "
        "select a.sym as s, b.rp as y insert into O;",
    ]
    app = shapes[seed % len(shapes)]
    assert both(app, mk_sends(90, keys=4, seed=100 + seed),
                flush_every=int(np.random.default_rng(seed).integers(1, 13)))
