"""Serving-plane end-to-end (siddhi_tpu/net): TCP/WS/shm ingest
byte-identical to in-process columnar ingest, credit backpressure,
admission shedding with replay, sink egress, telemetry surface."""
import threading
import time

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.net import (FrameReceiver, NetClientError, RingProducer,
                            TcpFrameClient, WsFrameClient)

# host-only execution throughout (HOST prefixed to every app): these
# tests verify TRANSPORT semantics (framing, admission, ordering, loss
# accounting), which are independent of the kernel backend — host apps
# skip every jit compile, keeping the suite inside the tier-1 budget.
# The device path over the wire is exercised end-to-end by
# `bench.py --net --smoke` (CI).
HOST = ("@app:deviceFilters('never')\n@app:devicePatterns('never')\n"
        "@app:deviceWindows('never')\n")
STOCK = "define stream StockStream (symbol string, price double, volume int);\n"
PATTERN_Q = ("@info(name='q') from every e1=StockStream[price > 100] -> "
             "e2=StockStream[price > e1.price] within 1 sec "
             "select e1.price as p1, e2.price as p2 insert into Out;\n")


def make_batches(n_batches=6, batch=64, seed=3):
    rng = np.random.default_rng(seed)
    ts0 = 1_700_000_000_000
    out = []
    for k in range(n_batches):
        out.append((
            {"symbol": np.array([f"K{i}" for i in
                                 rng.integers(0, 8, size=batch)]),
             "price": np.round(rng.uniform(90, 130, batch) * 4) / 4,
             "volume": rng.integers(1, 100, batch).astype(np.int32)},
            ts0 + np.arange(k * batch, (k + 1) * batch, dtype=np.int64)))
    return out


def run_inproc(app, batches, stream="StockStream"):
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(HOST + app)
    rows = []
    rt.add_batch_callback("Out", lambda b: rows.extend(
        map(tuple, b.rows(rt.strings))))
    rt.start()
    h = rt.input_handler(stream)
    for cols, ts in batches:
        h.send_batch(cols, ts)
    rt.flush()
    mgr.shutdown()
    return rows


def run_wire(app_head, app_body, batches, client_cls=TcpFrameClient,
             stream="StockStream"):
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(HOST + app_head + app_body)
    rows = []
    rt.add_batch_callback("Out", lambda b: rows.extend(
        map(tuple, b.rows(rt.strings))))
    rt.start()
    src = rt.sources[0]
    cols = client_cls.cols_of_schema(rt.schemas[stream])
    cli = client_cls("127.0.0.1", src.port, stream, cols)
    for c, ts in batches:
        cli.send_batch(c, ts)
    cli.barrier()
    cli.close()
    stats = rt.statistics()
    mgr.shutdown()
    return rows, stats


def test_tcp_ingest_byte_identical_to_inproc():
    batches = make_batches()
    host = run_inproc(STOCK + PATTERN_Q, batches)
    wire, stats = run_wire(
        "@source(type='tcp', port='0')\n" + STOCK, PATTERN_Q, batches)
    assert wire == host and len(wire) > 0
    net = stats["net"]["StockStream"]
    assert net["frames_in"] == len(batches)
    assert net["events_in"] == sum(len(t) for _, t in batches)
    assert net["shed_events"] == 0


def test_ws_ingest_byte_identical_to_inproc():
    batches = make_batches(n_batches=4)
    host = run_inproc(STOCK + PATTERN_Q, batches)
    wire, stats = run_wire(
        "@source(type='ws', port='0')\n" + STOCK, PATTERN_Q, batches,
        client_cls=WsFrameClient)
    assert wire == host and len(wire) > 0
    assert stats["net"]["StockStream"]["ws_connections"] == 1


def test_shm_ring_ingest_byte_identical_to_inproc():
    batches = make_batches(n_batches=4)
    host = run_inproc(STOCK + PATTERN_Q, batches)
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        HOST + "@source(type='shm', slots='8')\n" + STOCK + PATTERN_Q)
    rows = []
    rt.add_batch_callback("Out", lambda b: rows.extend(
        map(tuple, b.rows(rt.strings))))
    rt.start()
    src = rt.sources[0]
    prod = RingProducer(src.ring_name, "StockStream",
                        RingProducer.cols_of_schema(rt.schemas["StockStream"]))
    for c, ts in batches:
        prod.send_batch(c, ts)
    prod.barrier(timeout=10)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:       # consumer feed is async of
        rt.flush()                           # the ring drain barrier
        if len(rows) >= len(host):
            break
        time.sleep(0.01)
    prod.close()
    stats = rt.statistics()
    mgr.shutdown()
    assert rows == host and len(rows) > 0
    assert stats["net"]["StockStream"]["transport"] == "shm"


def test_shm_ring_split_batch_ships_strings_delta():
    """A batch too large for one ring slot splits into several DATA
    frames — and the oversize encode's STRINGS delta must still ship
    first, or every split frame's codes would be undeclared."""
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        HOST + "@source(type='shm', slots='8', slot.size='4096')\n"
        + STOCK + "@info(name='q') from StockStream select symbol, price "
                  "insert into Out;\n")
    rows = []
    rt.add_batch_callback("Out", lambda b: rows.extend(
        map(tuple, b.rows(rt.strings))))
    rt.start()
    prod = RingProducer(rt.sources[0].ring_name, "StockStream",
                        RingProducer.cols_of_schema(rt.schemas["StockStream"]))
    n = 1024                               # ~20 KB of columns >> 4 KB slot
    syms = np.array([f"SYM{i % 50}" for i in range(n)])
    prod.send_batch({"symbol": syms,
                     "price": np.arange(n, dtype=np.float64),
                     "volume": np.arange(n, dtype=np.int32)},
                    np.arange(n, dtype=np.int64))
    assert prod.frames_sent > 1            # actually split
    prod.barrier(timeout=10)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and len(rows) < n:
        rt.flush()
        time.sleep(0.01)
    stats = rt.statistics()
    prod.close()
    mgr.shutdown()
    assert stats["net"]["StockStream"].get("protocol_errors", 0) == 0
    assert [r[0] for r in rows] == list(syms)      # strings decode right
    assert [r[1] for r in rows] == list(np.arange(n, dtype=np.float64))


def test_encoder_casts_to_declared_wire_dtype():
    """An int array handed to a double column must ship double BITS —
    not get reinterpreted by the peer."""
    app = (HOST + "@source(type='tcp', port='0')\n" + STOCK
           + "@info(name='q') from StockStream select symbol, price "
             "insert into Out;\n")
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    rows = []
    rt.add_batch_callback("Out", lambda b: rows.extend(
        map(tuple, b.rows(rt.strings))))
    rt.start()
    cols = TcpFrameClient.cols_of_schema(rt.schemas["StockStream"])
    cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "StockStream", cols)
    cli.send_batch({"symbol": np.array(["A", "B"]),
                    "price": np.array([101, 102]),        # int64 input
                    "volume": np.array([7, 8])},          # int64 input
                   np.array([1, 2], dtype=np.int64))
    cli.barrier()
    assert rows == [("A", 101.0), ("B", 102.0)]
    cli.close()
    mgr.shutdown()


def test_two_connections_interleave_without_loss():
    app = (HOST + "@source(type='tcp', port='0')\n"
           + STOCK + "@info(name='q') from StockStream select symbol, "
                     "price insert into Out;\n")
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    n_out = [0]
    rt.add_batch_callback("Out", lambda b: n_out.__setitem__(0, n_out[0] + b.n))
    rt.start()
    port = rt.sources[0].port
    cols = TcpFrameClient.cols_of_schema(rt.schemas["StockStream"])

    def producer(seed):
        cli = TcpFrameClient("127.0.0.1", port, "StockStream", cols)
        for c, ts in make_batches(n_batches=4, batch=32, seed=seed):
            cli.send_batch(c, ts)
        cli.barrier()
        cli.close()

    threads = [threading.Thread(target=producer, args=(s,)) for s in (1, 2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rt.flush()
    assert n_out[0] == 2 * 4 * 32
    mgr.shutdown()


def test_credit_flow_is_granted():
    batches = make_batches(n_batches=40, batch=8)
    _, stats = run_wire(
        "@source(type='tcp', port='0', credit='4')\n" + STOCK,
        "@info(name='q') from StockStream select symbol insert into Out;\n",
        batches)
    net = stats["net"]["StockStream"]
    # 40 DATA frames against an initial credit of 4: the client must
    # have been re-credited many times to finish
    assert net["credit_granted"] >= 36
    assert net["frames_in"] == 40


def test_shed_policy_zero_unaccounted_loss_and_replay():
    app = (HOST + "@source(type='tcp', port='0', rate.limit='64', "
           "burst='64', shed.policy='shed')\n"
           + STOCK + "@info(name='q') from StockStream select symbol, "
                     "price insert into Out;\n")
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    n_out = [0]
    rt.add_batch_callback("Out", lambda b: n_out.__setitem__(0, n_out[0] + b.n))
    rt.start()
    cols = TcpFrameClient.cols_of_schema(rt.schemas["StockStream"])
    cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "StockStream", cols)
    batches = make_batches(n_batches=4, batch=32)      # 128 > 64 tokens
    for c, ts in batches:
        cli.send_batch(c, ts)
    cli.barrier()
    m = rt.admission["StockStream"].metrics()
    assert m["shed_events"] > 0
    assert n_out[0] + m["shed_events"] == 128          # nothing vanished
    assert len(rt.error_store) == m["shed_frames"]
    # replay restores the shed events through normal ingest
    rt.admission["StockStream"].set_rate_factor(1.0)
    rt.admission["StockStream"].bucket.rate = None     # lift the limit
    rep = rt.error_store.replay(rt)
    rt.flush()
    assert rep["remaining"] == 0 and n_out[0] == 128
    cli.close()
    mgr.shutdown()


def test_schema_mismatch_rejected_at_hello():
    app = HOST + "@source(type='tcp', port='0')\n" + STOCK + \
        "@info(name='q') from StockStream select symbol insert into Out;\n"
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    rt.start()
    port = rt.sources[0].port
    with pytest.raises(NetClientError, match="schema mismatch"):
        TcpFrameClient("127.0.0.1", port, "StockStream",
                       [("symbol", "string"), ("price", "double")])
    with pytest.raises(NetClientError, match="serves stream"):
        TcpFrameClient("127.0.0.1", port, "Other",
                       [("symbol", "string")])
    mgr.shutdown()


def test_mid_frame_disconnect_is_survivable():
    """A client dying mid-frame must not poison the server: later
    connections keep working and fully-received frames stay counted."""
    import socket
    from siddhi_tpu.net import frame as fp
    app = HOST + "@source(type='tcp', port='0')\n" + STOCK + \
        "@info(name='q') from StockStream select symbol insert into Out;\n"
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    n_out = [0]
    rt.add_batch_callback("Out", lambda b: n_out.__setitem__(0, n_out[0] + b.n))
    rt.start()
    port = rt.sources[0].port
    cols = TcpFrameClient.cols_of_schema(rt.schemas["StockStream"])
    # half a frame, then vanish
    raw = socket.create_connection(("127.0.0.1", port))
    blob = fp.encode_hello("", "StockStream",
                           [(n, t) for n, t in cols])
    raw.sendall(blob[:len(blob) // 2])
    raw.close()
    # garbage bytes, then vanish
    raw = socket.create_connection(("127.0.0.1", port))
    raw.sendall(b"\xde\xad\xbe\xef" * 4)
    raw.close()
    time.sleep(0.1)
    cli = TcpFrameClient("127.0.0.1", port, "StockStream", cols)
    for c, ts in make_batches(n_batches=2, batch=16):
        cli.send_batch(c, ts)
    cli.barrier()
    assert n_out[0] == 32
    cli.close()
    mgr.shutdown()


def test_net_feed_fault_captures_whole_frame():
    """An injected ingest fault after admission must capture the whole
    frame into the ErrorStore — the zero-loss invariant."""
    from siddhi_tpu.core.faults import FaultInjector
    app = HOST + "@source(type='tcp', port='0')\n" + STOCK + \
        "@info(name='q') from StockStream select symbol insert into Out;\n"
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    n_out = [0]
    rt.add_batch_callback("Out", lambda b: n_out.__setitem__(0, n_out[0] + b.n))
    rt.start()
    rt.fault_injector = FaultInjector(counts={"net.feed": 1})
    cols = TcpFrameClient.cols_of_schema(rt.schemas["StockStream"])
    cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "StockStream", cols)
    for c, ts in make_batches(n_batches=2, batch=16):
        cli.send_batch(c, ts)
    cli.barrier()
    assert n_out[0] == 16                  # second frame delivered
    assert len(rt.error_store) == 1        # first captured whole
    ent = rt.error_store.entries("StockStream")[0]
    assert ent.point == "net.feed" and len(ent.events) == 16
    rt.fault_injector = None
    rep = rt.error_store.replay(rt)
    rt.flush()
    assert rep["remaining"] == 0 and n_out[0] == 32
    cli.close()
    mgr.shutdown()


def test_slo_controller_lowers_admission_factor():
    """@app:latencySLO coupling: sustained p99 over an (unreachably
    tight) target must scale the net admission buckets down via the
    controller's admission_factor."""
    app = (HOST + "@app:latencySLO('0.001 ms')\n"
           "@source(type='tcp', port='0', rate.limit='1000000')\n"
           + STOCK + "@info(name='q') from StockStream select symbol "
                     "insert into Out;\n")
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    rt.start()
    cols = TcpFrameClient.cols_of_schema(rt.schemas["StockStream"])
    cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "StockStream", cols)
    deadline = time.monotonic() + 10
    batches = make_batches(n_batches=1, batch=8)
    while time.monotonic() < deadline:
        for c, ts in batches:
            cli.send_batch(c, ts)
        cli.barrier()
        if rt.admission["StockStream"].metrics()["rate_factor"] < 1.0:
            break
        time.sleep(0.02)
    m = rt.admission["StockStream"].metrics()
    slo = rt.statistics()["slo"]
    assert m["rate_factor"] < 1.0
    assert slo["admission_factor"] == m["rate_factor"]
    cli.close()
    mgr.shutdown()


def test_prometheus_net_series():
    batches = make_batches(n_batches=2, batch=16)
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(
        HOST + "@app:name('PromNet')\n@app:statistics('true')\n"
        "@source(type='tcp', port='0')\n" + STOCK +
        "@info(name='q') from StockStream select symbol insert into Out;\n")
    rt.start()
    cols = TcpFrameClient.cols_of_schema(rt.schemas["StockStream"])
    cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "StockStream", cols)
    for c, ts in batches:
        cli.send_batch(c, ts)
    cli.barrier()
    text = rt.stats.prometheus()
    assert ('siddhi_tpu_net_events_total{app="PromNet",'
            'stream="StockStream"} 32') in text
    assert "siddhi_tpu_net_frames_total" in text
    assert "siddhi_tpu_net_admission_factor" in text
    cli.close()
    mgr.shutdown()


# ---------------------------------------------------------------------------
# sink egress
# ---------------------------------------------------------------------------

def _egress_app(port, extra=""):
    return (HOST + STOCK.replace("StockStream", "S")
            + f"@sink(type='tcp', host='127.0.0.1', port='{port}'{extra})\n"
              "define stream Out (symbol string, price double);\n"
              "@info(name='q') from S[price > 100] select symbol, price "
              "insert into Out;\n")


def test_tcp_sink_batched_egress():
    rx = FrameReceiver()
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(_egress_app(rx.port))
    rt.start()
    h = rt.input_handler("S")
    h.send_batch({"symbol": ["A", "B", "C"], "price": [111.0, 5.0, 123.0],
                  "volume": [1, 2, 3]},
                 np.array([10, 11, 12], dtype=np.int64))
    rt.flush()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and len(rx.rows("Out")) < 2:
        time.sleep(0.01)
    assert rx.rows("Out") == [(10, ("A", 111.0)), (12, ("C", 123.0))]
    sink = rt.sinks[0]
    assert sink.frames_out == 1            # batched: ONE frame, 2 events
    mgr.shutdown()
    rx.stop()


def test_tcp_sink_retry_store_replay_roundtrip():
    rx = FrameReceiver()
    port = rx.port
    rx.stop()                              # peer down
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(_egress_app(
        port, ", on.error='store', max.retries='1', retry.interval='1 ms'"))
    with pytest.warns(RuntimeWarning, match="deferring"):
        rt.start()
    h = rt.input_handler("S")
    h.send_batch({"symbol": ["A"], "price": [111.0], "volume": [1]},
                 np.array([10], dtype=np.int64))
    rt.flush()
    assert len(rt.error_store) == 1        # captured after retries
    rx2 = FrameReceiver(port=port)         # peer recovers on same port
    rep = rt.error_store.replay(rt)
    assert rep["remaining"] == 0
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not rx2.rows("Out"):
        time.sleep(0.01)
    assert rx2.rows("Out") == [(10, ("A", 111.0))]
    mgr.shutdown()
    rx2.stop()


def test_ws_sink_roundtrip_via_net_source():
    """Engine-to-engine: a ws sink feeding another app's frame server."""
    mgr = SiddhiManager()
    rt_down = mgr.create_app_runtime(
        HOST + "@app:name('Down')\n@source(type='tcp', port='0')\n"
        "define stream Out (symbol string, price double);\n"
        "@info(name='q2') from Out select symbol insert into Final;\n")
    n_final = [0]
    rt_down.add_batch_callback(
        "Final", lambda b: n_final.__setitem__(0, n_final[0] + b.n))
    rt_down.start()
    port = rt_down.sources[0].port
    rt_up = mgr.create_app_runtime(
        HOST + "@app:name('Up')\n" + STOCK.replace("StockStream", "S")
        + f"@sink(type='ws', host='127.0.0.1', port='{port}')\n"
          "define stream Out (symbol string, price double);\n"
          "@info(name='q') from S[price > 100] select symbol, price "
          "insert into Out;\n")
    rt_up.start()
    rt_up.input_handler("S").send_batch(
        {"symbol": ["A", "B"], "price": [111.0, 5.0], "volume": [1, 2]},
        np.array([10, 11], dtype=np.int64))
    rt_up.flush()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and n_final[0] < 1:
        rt_down.flush()
        time.sleep(0.01)
    assert n_final[0] == 1
    mgr.shutdown()

def test_server_credit_disabled_client_does_not_deadlock():
    """HELLO_OK with credit 0 means the server negotiated crediting
    OFF — a default (credit-wanting) client must ship freely instead
    of blocking for CREDIT frames that will never come."""
    batches = make_batches(n_batches=3, batch=8)
    host = run_inproc(
        STOCK + "@info(name='q') from StockStream select symbol "
                "insert into Out;\n", batches)
    rows, stats = run_wire(
        "@source(type='tcp', port='0', credit='0')\n" + STOCK,
        "@info(name='q') from StockStream select symbol insert into Out;\n",
        batches)
    assert rows == host
    net = stats["net"]["StockStream"]
    assert net["frames_in"] == 3 and net["credit_granted"] == 0


def test_ws_sink_defers_on_hello_rejection():
    """A peer that is UP but rejects the negotiation (unknown stream →
    ERROR frame) must defer an armed ws sink to per-publish retry —
    the same contract the tcp sink honors — not crash rt.start()."""
    mgr = SiddhiManager()
    rt_down = mgr.create_app_runtime(
        HOST + "@app:name('D2')\n@source(type='tcp', port='0')\n"
        "define stream Different (x int);\n"
        "@info(name='q2') from Different select x insert into Sink2;\n")
    rt_down.start()
    port = rt_down.sources[0].port
    rt_up = mgr.create_app_runtime(
        HOST + "@app:name('U2')\n" + STOCK.replace("StockStream", "S")
        + f"@sink(type='ws', host='127.0.0.1', port='{port}', "
          "on.error='store', max.retries='1', retry.interval='1 ms')\n"
          "define stream Out (symbol string, price double);\n"
          "@info(name='q') from S[price > 100] select symbol, price "
          "insert into Out;\n")
    with pytest.warns(RuntimeWarning, match="deferring"):
        rt_up.start()
    rt_up.input_handler("S").send_batch(
        {"symbol": ["A"], "price": [111.0], "volume": [1]},
        np.array([10], dtype=np.int64))
    rt_up.flush()
    assert len(rt_up.error_store) == 1     # captured, engine alive
    mgr.shutdown()


def test_corrupt_frame_rejected_without_killing_connection():
    """A CRC-corrupted or truncated-payload DATA frame on a NEGOTIATED
    connection is rejected (ERROR frame, protocol_errors counted) while
    the SAME connection keeps serving: the length prefix consumed the
    bad frame whole, so framing stays aligned."""
    import socket
    from siddhi_tpu.net import frame as fp
    app = HOST + "@source(type='tcp', port='0')\n" + STOCK + \
        "@info(name='q') from StockStream select symbol insert into Out;\n"
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    n_out = [0]
    rt.add_batch_callback("Out", lambda b: n_out.__setitem__(0, n_out[0] + b.n))
    rt.start()
    sock = socket.create_connection(("127.0.0.1", rt.sources[0].port))
    read = fp.reader_for(sock)
    sock.sendall(fp.encode_hello(
        "", "StockStream", [("symbol", "string"), ("price", "double"),
                            ("volume", "int")], credit=False))
    assert fp.read_frame(read)[0] == fp.HELLO_OK
    sock.sendall(fp.encode_strings(["K0"], start_code=1))

    def data_blob(ts0):
        return fp.encode_data(
            np.arange(ts0, ts0 + 4, dtype=np.int64),
            [np.ones(4, np.int32), np.full(4, 101.0),
             np.arange(4, dtype=np.int32)])

    sock.sendall(data_blob(0))                      # good
    corrupt = bytearray(data_blob(4))
    corrupt[-6] ^= 0xFF                             # CRC now fails
    sock.sendall(bytes(corrupt))
    good = data_blob(8)
    # truncated PAYLOAD: valid frame envelope, short column buffers
    sock.sendall(fp.encode_frame(fp.DATA, good[8:-12]))
    sock.sendall(data_blob(12))                     # good again
    sock.sendall(fp.encode_ping(1))
    errors = 0
    while True:
        ftype, payload = fp.read_frame(read)
        if ftype == fp.ERROR:
            errors += 1
        elif ftype == fp.ACK:
            assert fp.decode_u64(payload) == 1
            break
    assert errors == 2                   # one per rejected frame
    assert n_out[0] == 8                 # both GOOD frames landed
    net = rt.statistics()["net"]["StockStream"]
    assert net["protocol_errors"] == 2
    assert net["shed_events"] == 0       # rejection is not shedding
    sock.close()
    mgr.shutdown()


def test_block_policy_backpressure_paces_producer():
    """Paced overload against a 'block'-policy rate limit: the server
    stops draining + withholds CREDIT, the producer stalls in
    _respect_credit, and every event is delivered — throughput capped,
    zero shed."""
    rate, burst = 1000.0, 64.0
    app = HOST + ("@source(type='tcp', port='0', "
                  f"rate.limit='{rate:.0f}', burst='{burst:.0f}', "
                  "shed.policy='block', credit='2')\n") + STOCK + \
        "@info(name='q') from StockStream select symbol insert into Out;\n"
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    n_out = [0]
    rt.add_batch_callback("Out", lambda b: n_out.__setitem__(0, n_out[0] + b.n))
    rt.start()
    cols = TcpFrameClient.cols_of_schema(rt.schemas["StockStream"])
    cli = TcpFrameClient("127.0.0.1", rt.sources[0].port, "StockStream",
                         cols)
    batches = make_batches(n_batches=6, batch=64)   # 384 events, 64 burst
    t0 = time.monotonic()
    for c, ts in batches:
        cli.send_batch(c, ts)           # stalls once credit dries up
    cli.barrier(timeout=60)
    elapsed = time.monotonic() - t0
    cli.close()
    m = rt.admission["StockStream"].metrics()
    assert n_out[0] == 384              # nothing shed, nothing lost
    assert m["shed_events"] == 0
    assert m["admitted_events"] == 384
    # 320 post-burst events at 1000 eps: the wire CANNOT finish faster
    # than the refill (generous lower bound only — no flaky upper)
    assert elapsed >= 0.25, elapsed
    assert m["blocked_seconds"] > 0.05
    mgr.shutdown()


def test_net_decode_fault_kills_connection_accountably():
    """An injected net.decode fault is connection-fatal like a corrupt
    frame off the wire: protocol_errors must tick and the server must
    keep serving new connections — the RuntimeError escaping the serve
    loop unhandled (dead thread, no accounting) is the regression."""
    from siddhi_tpu.core.faults import FaultInjector
    app = HOST + "@source(type='tcp', port='0')\n" + STOCK + \
        "@info(name='q') from StockStream select symbol insert into Out;\n"
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    n_out = [0]
    rt.add_batch_callback("Out", lambda b: n_out.__setitem__(0, n_out[0] + b.n))
    rt.start()
    rt.fault_injector = FaultInjector(counts={"net.decode": 1})
    cols = TcpFrameClient.cols_of_schema(rt.schemas["StockStream"])
    port = rt.sources[0].port
    cli = TcpFrameClient("127.0.0.1", port, "StockStream", cols)
    c, ts = make_batches(n_batches=1, batch=16)[0]
    cli.send_batch(c, ts)
    with pytest.raises(Exception):        # server drops the connection
        cli.barrier(timeout=10)
    try:
        cli.close()
    except OSError:
        pass
    rt.fault_injector = None
    deadline = time.monotonic() + 5       # accounting lands post-close
    while time.monotonic() < deadline \
            and rt.statistics()["net"]["StockStream"]["protocol_errors"] < 1:
        time.sleep(0.02)
    assert rt.statistics()["net"]["StockStream"]["protocol_errors"] >= 1
    cli2 = TcpFrameClient("127.0.0.1", port, "StockStream", cols)
    cli2.send_batch(c, ts)                # fresh connection serves fine
    cli2.barrier()
    assert n_out[0] == 16
    cli2.close()
    mgr.shutdown()


def test_ring_consumer_survives_producer_bye():
    """BYE ends one PRODUCER, not the ring: a second producer attaching
    to the same ring must still be consumed — the consumer thread used
    to exit permanently on the first BYE, stalling later producers."""
    app = HOST + "@source(type='shm')\n" + STOCK + \
        "@info(name='q') from StockStream select symbol insert into Out;\n"
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(app)
    n_out = [0]
    rt.add_batch_callback("Out", lambda b: n_out.__setitem__(0, n_out[0] + b.n))
    rt.start()
    cols = RingProducer.cols_of_schema(rt.schemas["StockStream"])
    c, ts = make_batches(n_batches=1, batch=16)[0]
    p1 = RingProducer(rt.sources[0].ring_name, "StockStream", cols)
    p1.send_batch(c, ts)
    p1.close()                             # sends BYE into the ring
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and n_out[0] < 16:
        rt.flush()
        time.sleep(0.02)
    assert n_out[0] == 16
    p2 = RingProducer(rt.sources[0].ring_name, "StockStream", cols)
    p2.send_batch(c, ts)                   # re-HELLOs, then DATA
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and n_out[0] < 32:
        rt.flush()
        time.sleep(0.02)
    assert n_out[0] == 32                  # consumer alive after BYE
    p2.close()
    mgr.shutdown()


def test_tcp_sink_ships_each_strings_delta_once():
    """Each payload's embedded STRINGS delta must advance the sink's
    peer-sync mark: re-shipping it as catch-up on the next publish
    doubles dictionary bytes on every high-cardinality stream."""
    rx = FrameReceiver()
    mgr = SiddhiManager()
    rt = mgr.create_app_runtime(_egress_app(rx.port))
    rt.start()
    h = rt.input_handler("S")
    h.send_batch({"symbol": ["A", "B"], "price": [111.0, 112.0],
                  "volume": [1, 2]}, np.array([10, 11], dtype=np.int64))
    rt.flush()
    h.send_batch({"symbol": ["C", "D"], "price": [113.0, 114.0],
                  "volume": [1, 2]}, np.array([12, 13], dtype=np.int64))
    rt.flush()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and len(rx.rows("Out")) < 4:
        time.sleep(0.01)
    assert len(rx.rows("Out")) == 4
    # connect-time table was empty (no replay); each payload embeds its
    # own delta; NO standalone catch-up frames may ride between them
    assert rx.strings_frames == 2
    mgr.shutdown()
    rx.stop()
