"""REST deployment service (reference: modules/siddhi-service,
SiddhiApi.java:31-63 deploy/undeploy surface)."""
import json
import urllib.request

import pytest

from siddhi_tpu.service import SiddhiService

APP = """
@app:name('RestApp')
define stream S (sym string, p double);
@PrimaryKey('sym')
define table T (sym string, p double);
@info(name='q') from S[p > 10] select sym, p update or insert into T
  on T.sym == sym;
"""


@pytest.fixture
def svc():
    s = SiddhiService(port=0).start()
    yield s
    s.stop()


def _post(svc, path, body, raw=False):
    data = body.encode() if raw else json.dumps(body).encode()
    req = urllib.request.Request(f"http://127.0.0.1:{svc.port}{path}",
                                 data=data, method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def _get(svc, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{svc.port}{path}") as r:
        return json.loads(r.read())


def test_deploy_event_query_undeploy(svc):
    r = _post(svc, "/siddhi/artifact/deploy", APP, raw=True)
    assert r == {"status": "deployed", "app": "RestApp"}
    assert _get(svc, "/siddhi/artifact/apps")["apps"] == ["RestApp"]

    _post(svc, "/siddhi/artifact/event",
          {"app": "RestApp", "stream": "S", "data": ["IBM", 42.0]})
    _post(svc, "/siddhi/artifact/event",
          {"app": "RestApp", "stream": "S", "data": ["ACME", 5.0]})
    rows = _post(svc, "/siddhi/artifact/query",
                 {"app": "RestApp", "query": "from T select sym, p"})["rows"]
    assert [r[1] for r in rows] == [["IBM", 42.0]]

    stats = _get(svc, "/siddhi/artifact/stats?siddhiApp=RestApp")
    assert "streams" in stats

    r = _get(svc, "/siddhi/artifact/undeploy?siddhiApp=RestApp")
    assert r["status"] == "undeployed"
    assert _get(svc, "/siddhi/artifact/apps")["apps"] == []


def test_bad_app_is_a_400(svc):
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(svc, "/siddhi/artifact/deploy", "define nonsense;", raw=True)
    assert e.value.code == 400


def test_stats_unknown_app_404(svc):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(svc, "/siddhi/artifact/stats?siddhiApp=Nope")
    assert e.value.code == 404
    assert "error" in json.loads(e.value.read())


def test_metrics_endpoint(svc):
    _post(svc, "/siddhi/artifact/deploy", APP, raw=True)
    for p in (11.0, 12.0, 3.0):
        _post(svc, "/siddhi/artifact/event",
              {"app": "RestApp", "stream": "S", "data": ["IBM", p]})
    with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.port}/metrics") as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        assert "version=0.0.4" in r.headers["Content-Type"]
        text = r.read().decode()
    # deployed runtimes are scrape-ready: stats on by default in service
    assert 'siddhi_tpu_events_total{app="RestApp",stream="S"} 3' in text
    assert "# HELP siddhi_tpu_events_total" in text
    assert "# TYPE siddhi_tpu_events_total counter" in text
    # per-app filter returns the same exposition
    with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.port}/metrics?siddhiApp=RestApp") as r:
        assert 'app="RestApp"' in r.read().decode()


def test_metrics_unknown_app_404(svc):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(svc, "/metrics?siddhiApp=Nope")
    assert e.value.code == 404
    assert "error" in json.loads(e.value.read())


def test_statistics_false_opts_out_of_service_stats(svc):
    _post(svc, "/siddhi/artifact/deploy",
          "@app:name('Quiet')\n@app:statistics('false')\n"
          "define stream S (x int);\nfrom S select x insert into O;\n",
          raw=True)
    _post(svc, "/siddhi/artifact/event",
          {"app": "Quiet", "stream": "S", "data": [1]})
    with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.port}/metrics?siddhiApp=Quiet") as r:
        text = r.read().decode()
    assert "siddhi_tpu_events_total" not in text
