"""REST deployment service (reference: modules/siddhi-service,
SiddhiApi.java:31-63 deploy/undeploy surface)."""
import json
import urllib.request

import pytest

from siddhi_tpu.service import SiddhiService

APP = """
@app:name('RestApp')
define stream S (sym string, p double);
@PrimaryKey('sym')
define table T (sym string, p double);
@info(name='q') from S[p > 10] select sym, p update or insert into T
  on T.sym == sym;
"""


@pytest.fixture
def svc():
    s = SiddhiService(port=0).start()
    yield s
    s.stop()


def _post(svc, path, body, raw=False):
    data = body.encode() if raw else json.dumps(body).encode()
    req = urllib.request.Request(f"http://127.0.0.1:{svc.port}{path}",
                                 data=data, method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def _get(svc, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{svc.port}{path}") as r:
        return json.loads(r.read())


def test_deploy_event_query_undeploy(svc):
    r = _post(svc, "/siddhi/artifact/deploy", APP, raw=True)
    assert r == {"status": "deployed", "app": "RestApp"}
    assert _get(svc, "/siddhi/artifact/apps")["apps"] == ["RestApp"]

    _post(svc, "/siddhi/artifact/event",
          {"app": "RestApp", "stream": "S", "data": ["IBM", 42.0]})
    _post(svc, "/siddhi/artifact/event",
          {"app": "RestApp", "stream": "S", "data": ["ACME", 5.0]})
    rows = _post(svc, "/siddhi/artifact/query",
                 {"app": "RestApp", "query": "from T select sym, p"})["rows"]
    assert [r[1] for r in rows] == [["IBM", 42.0]]

    stats = _get(svc, "/siddhi/artifact/stats?siddhiApp=RestApp")
    assert "streams" in stats

    r = _get(svc, "/siddhi/artifact/undeploy?siddhiApp=RestApp")
    assert r["status"] == "undeployed"
    assert _get(svc, "/siddhi/artifact/apps")["apps"] == []


def test_bad_app_is_a_400(svc):
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(svc, "/siddhi/artifact/deploy", "define nonsense;", raw=True)
    assert e.value.code == 400
