"""REST deployment service (reference: modules/siddhi-service,
SiddhiApi.java:31-63 deploy/undeploy surface)."""
import json
import urllib.request

import pytest

from siddhi_tpu.service import SiddhiService

APP = """
@app:name('RestApp')
define stream S (sym string, p double);
@PrimaryKey('sym')
define table T (sym string, p double);
@info(name='q') from S[p > 10] select sym, p update or insert into T
  on T.sym == sym;
"""


@pytest.fixture
def svc():
    s = SiddhiService(port=0).start()
    yield s
    s.stop()


def _post(svc, path, body, raw=False):
    data = body.encode() if raw else json.dumps(body).encode()
    req = urllib.request.Request(f"http://127.0.0.1:{svc.port}{path}",
                                 data=data, method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def _get(svc, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{svc.port}{path}") as r:
        return json.loads(r.read())


def test_deploy_event_query_undeploy(svc):
    r = _post(svc, "/siddhi/artifact/deploy", APP, raw=True)
    assert (r["status"], r["app"]) == ("deployed", "RestApp")
    # deploy responses carry the static-analysis findings (ANALYSIS.md)
    assert isinstance(r["diagnostics"], list)
    assert _get(svc, "/siddhi/artifact/apps")["apps"] == ["RestApp"]

    _post(svc, "/siddhi/artifact/event",
          {"app": "RestApp", "stream": "S", "data": ["IBM", 42.0]})
    _post(svc, "/siddhi/artifact/event",
          {"app": "RestApp", "stream": "S", "data": ["ACME", 5.0]})
    rows = _post(svc, "/siddhi/artifact/query",
                 {"app": "RestApp", "query": "from T select sym, p"})["rows"]
    assert [r[1] for r in rows] == [["IBM", 42.0]]

    stats = _get(svc, "/siddhi/artifact/stats?siddhiApp=RestApp")
    assert "streams" in stats

    r = _get(svc, "/siddhi/artifact/undeploy?siddhiApp=RestApp")
    assert r["status"] == "undeployed"
    assert _get(svc, "/siddhi/artifact/apps")["apps"] == []


def test_bad_app_is_a_400(svc):
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(svc, "/siddhi/artifact/deploy", "define nonsense;", raw=True)
    assert e.value.code == 400


def test_stats_unknown_app_404(svc):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(svc, "/siddhi/artifact/stats?siddhiApp=Nope")
    assert e.value.code == 404
    assert "error" in json.loads(e.value.read())


def test_metrics_endpoint(svc):
    _post(svc, "/siddhi/artifact/deploy", APP, raw=True)
    for p in (11.0, 12.0, 3.0):
        _post(svc, "/siddhi/artifact/event",
              {"app": "RestApp", "stream": "S", "data": ["IBM", p]})
    with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.port}/metrics") as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        assert "version=0.0.4" in r.headers["Content-Type"]
        text = r.read().decode()
    # deployed runtimes are scrape-ready: stats on by default in service
    assert 'siddhi_tpu_events_total{app="RestApp",stream="S"} 3' in text
    assert "# HELP siddhi_tpu_events_total" in text
    assert "# TYPE siddhi_tpu_events_total counter" in text
    # per-app filter returns the same exposition
    with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.port}/metrics?siddhiApp=RestApp") as r:
        assert 'app="RestApp"' in r.read().decode()


def test_metrics_unknown_app_404(svc):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(svc, "/metrics?siddhiApp=Nope")
    assert e.value.code == 404
    assert "error" in json.loads(e.value.read())


def test_statistics_false_opts_out_of_service_stats(svc):
    _post(svc, "/siddhi/artifact/deploy",
          "@app:name('Quiet')\n@app:statistics('false')\n"
          "define stream S (x int);\nfrom S select x insert into O;\n",
          raw=True)
    _post(svc, "/siddhi/artifact/event",
          {"app": "Quiet", "stream": "S", "data": [1]})
    with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.port}/metrics?siddhiApp=Quiet") as r:
        text = r.read().decode()
    assert "siddhi_tpu_events_total" not in text


# ---------------------------------------------------------------------------
# batch event endpoint (shared validation path)
# ---------------------------------------------------------------------------

def test_event_endpoint_batch_rows(svc):
    _post(svc, "/siddhi/artifact/deploy", APP, raw=True)
    r = _post(svc, "/siddhi/artifact/event",
              {"app": "RestApp", "stream": "S",
               "data": [["IBM", 42.0], ["ACME", 5.0], ["WSO2", 77.0]]})
    assert r == {"status": "ok", "events": 3}
    rows = _post(svc, "/siddhi/artifact/query",
                 {"app": "RestApp", "query": "from T select sym, p"})["rows"]
    assert sorted(r[1][0] for r in rows) == ["IBM", "WSO2"]


def test_event_endpoint_events_form_with_timestamps(svc):
    _post(svc, "/siddhi/artifact/deploy", APP, raw=True)
    r = _post(svc, "/siddhi/artifact/event",
              {"app": "RestApp", "stream": "S",
               "events": [{"data": ["IBM", 42.0], "timestamp": 1000},
                          {"data": ["WSO2", 77.0]}]})
    assert r["events"] == 2


@pytest.mark.parametrize("body,frag", [
    ({"app": "RestApp", "stream": "S", "data": [["IBM"]]},
     "expects 2 attributes"),
    ({"app": "RestApp", "stream": "S", "data": "nope"}, "must be a list"),
    ({"app": "RestApp", "stream": "Nope", "data": ["IBM", 1.0]},
     "no stream"),
    ({"app": "Nope", "stream": "S", "data": ["IBM", 1.0]},
     "no deployed app"),
    ({"app": "RestApp", "stream": "S",
      "events": [{"nodata": 1}]}, "events[0]"),
    ({"app": "RestApp", "stream": "S", "data": ["IBM", 1.0],
      "timestamp": "soon"}, "must be a number"),
])
def test_event_endpoint_malformed_is_400_json(svc, body, frag):
    _post(svc, "/siddhi/artifact/deploy", APP, raw=True)
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(svc, "/siddhi/artifact/event", body)
    assert e.value.code == 400
    assert frag in json.loads(e.value.read())["error"]


def test_event_endpoint_non_json_body_is_400(svc):
    _post(svc, "/siddhi/artifact/deploy", APP, raw=True)
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(svc, "/siddhi/artifact/event", "{not json", raw=True)
    assert e.value.code == 400
    assert "not JSON" in json.loads(e.value.read())["error"]


# ---------------------------------------------------------------------------
# serving data plane (siddhi_tpu/net) front door
# ---------------------------------------------------------------------------

NET_APP = """
@app:name('NetFront')
@app:deviceFilters('never')
define stream S (sym string, p double);
@info(name='q') from S[p > 10] select sym, p insert into Out;
"""


def _net_client(svc, app="NetFront", stream="S", credit=True):
    from siddhi_tpu.net import TcpFrameClient
    rt = svc.runtimes[app]
    cols = TcpFrameClient.cols_of_schema(rt.schemas[stream])
    return TcpFrameClient("127.0.0.1", svc.net_port, stream, cols,
                          app=app, credit=credit)


def test_service_data_plane_feeds_deployed_app(svc):
    import numpy as np
    _post(svc, "/siddhi/artifact/deploy", NET_APP, raw=True)
    rt = svc.runtimes["NetFront"]
    n_out = [0]
    rt.add_batch_callback("Out", lambda b: n_out.__setitem__(0, n_out[0] + b.n))
    cli = _net_client(svc)
    cli.send_batch({"sym": np.array(["A", "B"]),
                    "p": np.array([11.0, 5.0])},
                   np.array([1, 2], dtype=np.int64))
    cli.barrier()
    assert n_out[0] == 1
    info = _get(svc, "/siddhi/net")
    assert info["enabled"] and info["port"] == svc.net_port
    assert info["streams"]["NetFront/S"]["events_in"] == 2
    cli.close()


def test_service_net_unknown_app_rejected(svc):
    from siddhi_tpu.net import NetClientError, TcpFrameClient
    with pytest.raises(NetClientError, match="no deployed app"):
        TcpFrameClient("127.0.0.1", svc.net_port, "S",
                       [("sym", "string")], app="Ghost")


def test_deploy_undeploy_racing_ingest_never_drops_admitted_frames(svc):
    """The satellite invariant: deploy/undeploy racing live data-plane
    ingest on another thread never drops or double-delivers an admitted
    frame; admitted-then-undeployed frames land in the ErrorStore."""
    import threading
    import numpy as np
    _post(svc, "/siddhi/artifact/deploy", NET_APP, raw=True)
    rt = svc.runtimes["NetFront"]
    delivered = [0]
    rt.add_batch_callback("S", lambda b: delivered.__setitem__(
        0, delivered[0] + b.n))
    cli = _net_client(svc, credit=False)
    sent = [0]
    stop = [False]

    def feeder():
        while not stop[0]:
            try:
                cli.send_batch({"sym": np.array(["Z"]),
                                "p": np.array([99.0])},
                               np.array([sent[0]], dtype=np.int64))
                sent[0] += 1
            except Exception:
                return

    t = threading.Thread(target=feeder)
    t.start()
    try:
        import time
        time.sleep(0.05)
        _get(svc, "/siddhi/artifact/undeploy?siddhiApp=NetFront")
        time.sleep(0.05)
    finally:
        stop[0] = True
        t.join()
        cli.close()
    import time
    time.sleep(0.3)                       # server drains its socket
    store = svc.retired_errors["NetFront"]
    parked = sum(len(e.events or ()) for e in store.entries("S")
                 if e.point == "net.undeployed")
    # every event the server ADMITTED is either delivered-live or
    # parked in the ErrorStore — exactly once each.  (Frames still in
    # the client's socket buffer at close were never admitted.)
    admitted = rt.admission["S"].metrics()["admitted_events"]
    assert delivered[0] + parked == admitted
    assert parked > 0                     # the race actually happened
    assert delivered[0] > 0


def test_redeploy_same_name_serves_new_runtime(svc):
    import numpy as np
    _post(svc, "/siddhi/artifact/deploy", NET_APP, raw=True)
    cli = _net_client(svc)
    cli.send_batch({"sym": np.array(["A"]), "p": np.array([11.0])},
                   np.array([1], dtype=np.int64))
    cli.barrier()
    old_rt = svc.runtimes["NetFront"]
    _post(svc, "/siddhi/artifact/deploy", NET_APP, raw=True)  # redeploy
    new_rt = svc.runtimes["NetFront"]
    assert new_rt is not old_rt
    # the OLD connection's frames now park in the old store (old rt is
    # a zombie), while a NEW connection reaches the new runtime
    cli.send_batch({"sym": np.array(["B"]), "p": np.array([12.0])},
                   np.array([2], dtype=np.int64))
    cli.barrier()
    assert any(e.point == "net.undeployed"
               for e in old_rt.error_store.entries("S"))
    n_out = [0]
    new_rt.add_batch_callback("Out", lambda b: n_out.__setitem__(
        0, n_out[0] + b.n))
    cli2 = _net_client(svc)
    cli2.send_batch({"sym": np.array(["C"]), "p": np.array([13.0])},
                    np.array([3], dtype=np.int64))
    cli2.barrier()
    assert n_out[0] == 1
    cli.close()
    cli2.close()


def test_stop_joins_handler_threads_bounded():
    """Service teardown is clean and bounded even with handler threads
    that served requests (daemon_threads + tracked joins)."""
    import time
    s = SiddhiService(port=0).start()
    _post(s, "/siddhi/artifact/deploy", APP, raw=True)
    for _ in range(3):
        _get(s, "/siddhi/artifact/apps")
    t0 = time.monotonic()
    s.stop()
    assert time.monotonic() - t0 < 10.0
    assert s.httpd._handler_threads == []
    # idempotent-ish: a second stop must not raise
    import threading
    assert all(not t.is_alive() for t in threading.enumerate()
               if t.name.startswith("siddhi-service-net"))

def test_retired_errors_listable_and_replayable_after_redeploy(svc):
    """Frames parked by an undeploy stay reachable through the errors
    API: listable while the name is down, replayable once it returns."""
    _post(svc, "/siddhi/artifact/deploy", NET_APP, raw=True)
    rt = svc.runtimes["NetFront"]
    rt.error_store.add("S", "net.undeployed", "undeployed mid-feed", 1,
                       events=[(1, ("A", 11.0))])
    _get(svc, "/siddhi/artifact/undeploy?siddhiApp=NetFront")
    errs = _get(svc, "/siddhi/errors?siddhiApp=NetFront")["errors"]
    assert len(errs) == 1 and errs[0]["parked"] is True
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(svc, "/siddhi/errors", {"app": "NetFront", "action": "replay"})
    assert e.value.code == 400
    assert "redeploy" in json.loads(e.value.read())["error"]
    _post(svc, "/siddhi/artifact/deploy", NET_APP, raw=True)
    new_rt = svc.runtimes["NetFront"]
    n_out = [0]
    new_rt.add_batch_callback("Out", lambda b: n_out.__setitem__(
        0, n_out[0] + b.n))
    rep = _post(svc, "/siddhi/errors", {"app": "NetFront", "action": "replay"})
    assert rep["replayed"] == 1 and rep["remaining"] == 0
    assert n_out[0] == 1
    assert not _get(svc, "/siddhi/errors?siddhiApp=NetFront")["errors"]


def test_rehello_rebinds_connection_and_resets_string_state(svc):
    """A second HELLO re-negotiates the connection: the string remap
    restarts with it, so codes from the previous binding can never leak
    into the new runtime — reuse without a fresh delta fails loudly."""
    import socket
    import numpy as np
    from siddhi_tpu.net import frame as fp
    _post(svc, "/siddhi/artifact/deploy", NET_APP, raw=True)
    _post(svc, "/siddhi/artifact/deploy",
          NET_APP.replace("NetFront", "NetFrontB"), raw=True)
    rt_a = svc.runtimes["NetFront"]
    rt_b = svc.runtimes["NetFrontB"]
    out_a, out_b = [], []
    rt_a.add_batch_callback("Out", lambda b: out_a.extend(
        map(tuple, b.rows(rt_a.strings))))
    rt_b.add_batch_callback("Out", lambda b: out_b.extend(
        map(tuple, b.rows(rt_b.strings))))
    cols = [("sym", "string"), ("p", "double")]
    sock = socket.create_connection(("127.0.0.1", svc.net_port))
    read = fp.reader_for(sock)
    sock.sendall(fp.encode_hello("NetFront", "S", cols, credit=False))
    assert fp.read_frame(read)[0] == fp.HELLO_OK
    sock.sendall(fp.encode_strings(["AAA"], start_code=1))
    sock.sendall(fp.encode_data(np.array([1], dtype=np.int64),
                                [np.array([1], dtype=np.int32),
                                 np.array([11.0])]))
    sock.sendall(fp.encode_ping(1))
    while fp.read_frame(read)[0] != fp.ACK:
        pass
    assert out_a == [("AAA", 11.0)]
    # re-HELLO to app B: a fresh delta re-using start code 1 must bind
    # cleanly to the NEW runtime
    sock.sendall(fp.encode_hello("NetFrontB", "S", cols, credit=False))
    assert fp.read_frame(read)[0] == fp.HELLO_OK
    sock.sendall(fp.encode_strings(["BBB"], start_code=1))
    sock.sendall(fp.encode_data(np.array([2], dtype=np.int64),
                                [np.array([1], dtype=np.int32),
                                 np.array([12.0])]))
    sock.sendall(fp.encode_ping(2))
    while fp.read_frame(read)[0] != fp.ACK:
        pass
    assert out_b == [("BBB", 12.0)]
    # re-HELLO back to A, then DATA WITHOUT re-shipping the dictionary:
    # the stale codes must be rejected loudly, never silently remapped
    sock.sendall(fp.encode_hello("NetFront", "S", cols, credit=False))
    assert fp.read_frame(read)[0] == fp.HELLO_OK
    sock.sendall(fp.encode_data(np.array([3], dtype=np.int64),
                                [np.array([1], dtype=np.int32),
                                 np.array([13.0])]))
    ftype, payload = fp.read_frame(read)
    assert ftype == fp.ERROR
    assert "never declared" in json.loads(payload)["error"]
    sock.close()
    assert out_a == [("AAA", 11.0)]        # nothing leaked into A


RATED_APP = """
@app:name('RatedRest')
@app:deviceFilters('never')
@source(type='tcp', port='0', rate.limit='2', burst='5',
        shed.policy='shed')
define stream S (sym string, p double);
@info(name='q') from S[p > 10] select sym, p insert into Out;
"""


def test_rest_event_shares_admission_quota_and_sheds(svc):
    """REST ingest rides the SAME admission controller as the frame
    plane: past the token bucket it sheds into the replayable
    ErrorStore with a 429 — and replay restores every event."""
    import urllib.error
    _post(svc, "/siddhi/artifact/deploy", RATED_APP, raw=True)
    rt = svc.runtimes["RatedRest"]
    delivered = [0]
    rt.add_batch_callback("S", lambda b: delivered.__setitem__(
        0, delivered[0] + b.n))
    codes = []
    for i in range(8):                   # burst=5: the tail must shed
        try:
            r = _post(svc, "/siddhi/artifact/event",
                      {"app": "RatedRest", "stream": "S",
                       "data": [f"K{i}", 11.0 + i], "timestamp": 1000 + i})
            codes.append(("ok", r))
        except urllib.error.HTTPError as e:
            body = json.loads(e.read())
            codes.append((e.code, body))
    oks = [c for c in codes if c[0] == "ok"]
    sheds = [c for c in codes if c[0] == 429]
    assert len(oks) + len(sheds) == 8 and sheds, codes
    assert all(b["status"] == "shed" and b["stored"] for _, b in sheds)
    m = rt.admission["S"].metrics()
    assert m["shed_events"] == len(sheds)
    assert m["admitted_events"] == len(oks)
    # shared accounting surfaces in /metrics alongside the frame plane
    with urllib.request.urlopen(
            f"http://127.0.0.1:{svc.port}/metrics?siddhiApp=RatedRest") as r:
        text = r.read().decode()
    assert f'siddhi_tpu_net_shed_events_total{{app="RatedRest",' \
           f'stream="S"}} {len(sheds)}' in text
    # zero silent loss: lift the limit, replay restores every shed event
    rt.admission["S"].bucket.rate = None
    rep = _post(svc, "/siddhi/errors", {"app": "RatedRest",
                                        "action": "replay"})
    rt.flush()
    assert rep["remaining"] == 0
    assert delivered[0] == 8


def test_rest_event_unlimited_stream_still_accounted(svc):
    """An app with NO net source gets a default (unlimited) controller
    on first REST ingest, so REST telemetry shows up in the net
    section either way."""
    _post(svc, "/siddhi/artifact/deploy", APP, raw=True)
    r = _post(svc, "/siddhi/artifact/event",
              {"app": "RestApp", "stream": "S",
               "data": [["A", 11.0], ["B", 12.0]]})
    assert r == {"status": "ok", "events": 2}
    m = svc.runtimes["RestApp"].admission["S"].metrics()
    assert m["admitted_events"] == 2 and m["shed_events"] == 0
    assert m["frames_in"] == 1           # one REST batch = one "frame"


def test_park_merge_preserves_prior_generation(svc):
    """Two undeploy cycles of the same name with unreplayed entries:
    the first generation's entries must merge into the newly parked
    store (oldest first) — never orphaned in a store nothing lists."""
    _post(svc, "/siddhi/artifact/deploy", NET_APP, raw=True)
    svc.runtimes["NetFront"].error_store.add(
        "S", "net.shed", "gen1", 1, events=[(1, ("A", 11.0))])
    _get(svc, "/siddhi/artifact/undeploy?siddhiApp=NetFront")
    _post(svc, "/siddhi/artifact/deploy", NET_APP, raw=True)
    svc.runtimes["NetFront"].error_store.add(
        "S", "net.shed", "gen2", 2, events=[(2, ("B", 12.0))])
    _get(svc, "/siddhi/artifact/undeploy?siddhiApp=NetFront")
    errs = _get(svc, "/siddhi/errors?siddhiApp=NetFront")["errors"]
    assert [e["error"] for e in errs] == ["gen1", "gen2"]    # oldest first
    assert all(e["parked"] for e in errs)
    _post(svc, "/siddhi/artifact/deploy", NET_APP, raw=True)
    rt = svc.runtimes["NetFront"]
    seen = []
    rt.add_batch_callback("Out", lambda b: seen.extend(
        map(tuple, b.rows(rt.strings))))
    rep = _post(svc, "/siddhi/errors", {"app": "NetFront",
                                        "action": "replay"})
    rt.flush()
    assert rep["replayed"] == 2 and rep["remaining"] == 0
    assert sorted(seen) == [("A", 11.0), ("B", 12.0)]


def test_errors_action_ids_resolve_live_before_parked(svc):
    """Live and parked stores number entries independently: an explicit
    id aimed at a live entry must not also consume the unrelated parked
    entry holding the same id."""
    _post(svc, "/siddhi/artifact/deploy", NET_APP, raw=True)
    svc.runtimes["NetFront"].error_store.add(
        "S", "net.shed", "parked-one", 1, events=[(1, ("A", 11.0))])
    _get(svc, "/siddhi/artifact/undeploy?siddhiApp=NetFront")
    _post(svc, "/siddhi/artifact/deploy", NET_APP, raw=True)
    live = svc.runtimes["NetFront"].error_store
    live.add("S", "net.shed", "live-one", 2, events=[(2, ("B", 12.0))])
    live_id = live.entries("S")[0].id
    parked_id = svc.retired_errors["NetFront"].entries("S")[0].id
    assert live_id == parked_id          # the collision under test
    r = _post(svc, "/siddhi/errors", {"app": "NetFront",
                                      "action": "discard",
                                      "ids": [live_id]})
    assert r == {"discarded": 1, "remaining": 1}
    errs = _get(svc, "/siddhi/errors?siddhiApp=NetFront")["errors"]
    assert [e["error"] for e in errs] == ["parked-one"]
    assert errs[0]["parked"] is True


OLDEST_APP = """
@app:name('OldestRest')
@app:deviceFilters('never')
@source(type='tcp', port='0', rate.limit='5', burst='5',
        shed.policy='oldest')
define stream S (sym string, p double);
@info(name='q') from S select sym, p insert into Out;
"""


def test_rest_type_bad_value_is_400_not_engine_poison(svc):
    """A type-bad value (string where a double belongs) passes the old
    arity-only validation, gets buffered by rt.send, and then fails at
    flush INSIDE the batch builder — breaking every later flush of the
    app.  It must 400 at the boundary and leave the app healthy."""
    import urllib.error
    _post(svc, "/siddhi/artifact/deploy", OLDEST_APP, raw=True)
    rt = svc.runtimes["OldestRest"]
    delivered = []
    rt.add_callback("Out", lambda evs: delivered.extend(e.data for e in evs))
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(svc, "/siddhi/artifact/event",
              {"app": "OldestRest", "stream": "S",
               "data": ["bad", "not-a-double"]})
    assert ei.value.code == 400
    assert "expects a number" in json.loads(ei.value.read())["error"]
    # the app is NOT poisoned: a valid event still flows end to end
    r = _post(svc, "/siddhi/artifact/event",
              {"app": "OldestRest", "stream": "S", "data": ["good", 99.0]})
    assert r["status"] == "ok"
    rt.flush()
    assert ("good", 99.0) in delivered


def test_rest_queued_bad_batch_cannot_poison_later_requests(svc):
    """A queued ('oldest') REST batch whose feed raises — type-bad data
    passes arity validation and fails at flush — must capture into the
    ErrorStore when drained, NOT fail whichever unrelated request (or
    connection thread) happened to drain it."""
    import time

    from siddhi_tpu.net.admission import Work
    _post(svc, "/siddhi/artifact/deploy", OLDEST_APP, raw=True)
    rt = svc.runtimes["OldestRest"]
    delivered = []
    rt.add_callback("Out", lambda evs: delivered.extend(e.data for e in evs))
    rt._pump_admission = lambda: None    # only REST drains the queue
    r = _post(svc, "/siddhi/artifact/event",
              {"app": "OldestRest", "stream": "S",
               "data": [["K0", 1.0]]})
    assert r["status"] == "ok"
    ctrl = rt.admission["S"]

    def boom():
        raise RuntimeError("synthetic feed failure")

    poison = Work(n=1, nbytes=10, feed=boom,
                  rows=lambda: [(0, ("X", 0.0))], stream_id="S")
    with ctrl._lock:                     # park a poisoned queue head
        ctrl._pending.append(poison)
        ctrl.pending_bytes += poison.nbytes
    time.sleep(0.3)                      # tokens refill for the head
    # a VALID request drains the poisoned head: it must never see an
    # error for someone else's work
    r = _post(svc, "/siddhi/artifact/event",
              {"app": "OldestRest", "stream": "S", "data": ["good", 99.0]})
    assert r["status"] in ("ok", "queued")
    bad = [e for e in rt.error_store.entries("S") if e.point == "net.feed"]
    assert len(bad) == 1                 # captured, not vanished
    assert bad[0].events[0][1] == ("X", 0.0)
    del rt.__dict__["_pump_admission"]   # let the scheduler pump resume
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and ("good", 99.0) not in delivered:
        time.sleep(0.02)
    assert ("good", 99.0) in delivered   # the valid event still lands


# ---------------------------------------------------------------------------
# EXPLAIN plane (docs/ANALYSIS.md): endpoint == rt.explain(), verbatim
# ---------------------------------------------------------------------------

def _get_raw(svc, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{svc.port}{path}") as r:
        return r.read()


def test_explain_endpoint_byte_identical_for_bench_configs(svc):
    """Acceptance: GET /siddhi/artifact/explain and rt.explain() agree
    byte-for-byte on placement + reasons for every bench config app
    (filter / window / pattern / partitioned pattern / join)."""
    import warnings

    import bench

    apps = {
        "B1": bench.DEV["filters"] + bench.C1,
        "B2": bench.DEV["windows"] + bench.C2,
        "B3": bench.DEV["patterns"] + bench.C3,
        "B4": bench.DEV["patterns"] + bench.C4,
        "B6": bench.JOIN_APP,
    }
    for name, app in apps.items():
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            _post(svc, "/siddhi/artifact/deploy",
                  f"@app:name('{name}')\n" + app, raw=True)
        body = _get_raw(svc, f"/siddhi/artifact/explain?siddhiApp={name}")
        rt = svc.runtimes[name]
        assert body == json.dumps(rt.explain()).encode(), name
        ex = json.loads(body)
        assert ex["app"] == name
        assert ex["placement"]["device"] + ex["placement"]["interpreter"] \
            >= 1, name
        _get(svc, f"/siddhi/artifact/undeploy?siddhiApp={name}")


def test_explain_endpoint_unknown_app_404(svc):
    import urllib.error
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(svc, "/siddhi/artifact/explain?siddhiApp=Nope")
    assert ei.value.code == 404


def test_deploy_reports_diagnostics_and_strict_rejects(svc):
    r = _post(svc, "/siddhi/artifact/deploy",
              "@app:name('Lint')\n"
              "define stream S (v double);\n"
              "@info(name='q') from S select avg(v) as m insert into Out;\n",
              raw=True)
    ids = [d["rule_id"] for d in r["diagnostics"]]
    assert "SA02" in ids
    _get(svc, "/siddhi/artifact/undeploy?siddhiApp=Lint")

    # @app:strictAnalysis: the same app is REFUSED, with structured
    # findings in the 400 body
    import urllib.error
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(svc, "/siddhi/artifact/deploy",
              "@app:name('LintStrict') @app:strictAnalysis\n"
              "define stream S (v double);\n"
              "@info(name='q') from S select avg(v) as m insert into Out;\n",
              raw=True)
    assert ei.value.code == 400
    body = json.loads(ei.value.read())
    assert "strictAnalysis" in body["error"]
    assert any(d["rule_id"] == "SA02" for d in body["diagnostics"])
    assert "LintStrict" not in _get(svc, "/siddhi/artifact/apps")["apps"]
