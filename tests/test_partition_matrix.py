"""Partition scenario matrix (reference: siddhi-core query/partition/
PartitionTestCase1/2.java shapes — per-key isolation across query
kinds, inner streams, range partitions, key cardinality growth).
Complements test_partitions.py with table-driven breadth (VERDICT r3
#8)."""
import pytest

from siddhi_tpu import SiddhiManager

HEAD = ("@app:playback define stream S (sym string, p double, v long);\n")


def run(app, sends, out="O"):
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    rows = []
    rt.add_callback(out, lambda evs: rows.extend(
        (e.timestamp, tuple(e.data)) for e in evs))
    rt.start()
    h = rt.input_handler("S")
    for i, (row, ts) in enumerate(sends):
        h.send(row, timestamp=ts)
        if i % 4 == 3:
            rt.flush()
    rt.flush()
    m.shutdown()
    return rows


TAPE = [((f"K{i % 3}", float(10 + i), i % 5), 1000 + i * 10)
        for i in range(24)]


def by_key(rows, idx=0):
    out: dict = {}
    for ts, r in rows:
        out.setdefault(r[idx], []).append((ts, r))
    return out


def test_partitioned_filter_projection():
    app = HEAD + """
    partition with (sym of S) begin
      @info(name='q') from S[p > 12] select sym, p * 2 as d insert into O;
    end;
    """
    rows = run(app, TAPE)
    # per-key streams see only their events; all passing events emitted
    want = [(ts, (sym, p * 2)) for (sym, p, v), ts in TAPE if p > 12]
    assert sorted(rows) == sorted(want)


def test_partitioned_window_sum_isolated_per_key():
    app = HEAD + """
    partition with (sym of S) begin
      @info(name='q') from S#window.length(2) select sym, sum(p) as s
      insert into O;
    end;
    """
    rows = run(app, TAPE)
    per = by_key(rows)
    for key, krows in per.items():
        feed = [p for (sym, p, v), _ts in TAPE if sym == key]
        want = [sum(feed[max(0, i - 1):i + 1]) for i in range(len(feed))]
        assert [r[1][1] for r in krows] == pytest.approx(want), key


def test_partitioned_count_aggregate():
    app = HEAD + """
    partition with (sym of S) begin
      @info(name='q') from S select sym, count() as c insert into O;
    end;
    """
    rows = run(app, TAPE)
    per = by_key(rows)
    for key, krows in per.items():
        n = sum(1 for (sym, _p, _v), _ts in TAPE if sym == key)
        assert [r[1][1] for r in krows] == list(range(1, n + 1)), key


def test_inner_stream_chains_stay_per_key():
    app = HEAD + """
    partition with (sym of S) begin
      @info(name='a') from S select sym, p + 1 as p1 insert into #mid;
      @info(name='b') from #mid[p1 > 13] select sym, p1 insert into O;
    end;
    """
    rows = run(app, TAPE)
    want = [(ts, (sym, p + 1)) for (sym, p, v), ts in TAPE if p + 1 > 13]
    assert sorted(rows) == sorted(want)


def test_range_partition_buckets():
    app = HEAD + """
    partition with (p < 15 as 'low' or p >= 15 as 'high' of S) begin
      @info(name='q') from S select sym, count() as c insert into O;
    end;
    """
    rows = run(app, TAPE)
    lo = sum(1 for (sym, p, v), _ts in TAPE if p < 15)
    hi = len(TAPE) - lo
    # every event lands in exactly one bucket; each bucket's count runs
    # 1..population, so the max count seen equals the larger bucket
    assert len(rows) == len(TAPE)
    assert max(c for _ts, (_sym, c) in rows) == max(lo, hi)


def test_partitioned_pattern_per_key_device_vs_host():
    body = HEAD + """
    partition with (sym of S) begin
      @info(name='q') from every e1=S[p > 11] -> e2=S[p > e1.p]
      within 1 sec select e1.p as a, e2.p as b insert into O;
    end;
    """
    dev = run("@app:devicePatterns('always')\n" + body, TAPE)
    host = run("@app:devicePatterns('never')\n" + body, TAPE)
    assert sorted(dev) == sorted(host) and dev


def test_partitioned_sequence_strictness_per_key():
    # strictness applies within the key's sub-stream: other keys'
    # events must NOT break a key's contiguity
    app = HEAD + """
    partition with (sym of S) begin
      @info(name='q') from every e1=S[p > 0], e2=S[p > e1.p]
      select e1.sym as sym, e1.p as a, e2.p as b insert into O;
    end;
    """
    sends = [(("A", 1.0, 0), 1000), (("B", 50.0, 0), 1001),
             (("A", 2.0, 0), 1002), (("B", 10.0, 0), 1003),
             (("A", 1.5, 0), 1004)]
    rows = run(app, sends)
    assert sorted(r for _ts, r in rows) == [("A", 1.0, 2.0)]


def test_key_cardinality_growth_preserves_isolation():
    sends = [((f"K{i % 11}", float(i), 1), 1000 + i) for i in range(66)]
    app = ("@app:partitionCapacity(4)\n" + HEAD + """
    partition with (sym of S) begin
      @info(name='q') from S select sym, count() as c insert into O;
    end;
    """)
    rows = run(app, sends)
    per = by_key(rows)
    assert len(per) == 11
    for key, krows in per.items():
        assert [r[1][1] for r in krows] == list(range(1, 7)), key


def test_two_partitions_do_not_interfere():
    app = HEAD + """
    partition with (sym of S) begin
      @info(name='q1') from S select sym, count() as c insert into O;
    end;
    partition with (v of S) begin
      @info(name='q2') from S select v, count() as c insert into O2;
    end;
    """
    m = SiddhiManager()
    rt = m.create_app_runtime(app)
    o1, o2 = [], []
    rt.add_callback("O", lambda evs: o1.extend(tuple(e.data) for e in evs))
    rt.add_callback("O2", lambda evs: o2.extend(tuple(e.data) for e in evs))
    rt.start()
    h = rt.input_handler("S")
    for (row, ts) in TAPE:
        h.send(row, timestamp=ts)
    rt.flush()
    m.shutdown()
    assert len(o1) == len(TAPE) and len(o2) == len(TAPE)
    assert max(c for _s, c in o1) == 8      # 24 events / 3 syms
    assert max(c for _v, c in o2) == 5      # v cycles 0..4 over 24


def test_partitioned_snapshot_restore_continuity():
    app = ("@app:devicePatterns('always')\n" + HEAD + """
    partition with (sym of S) begin
      @info(name='q') from every e1=S[p > 11] -> e2=S[p > e1.p]
      within 10 sec select e1.p as a, e2.p as b insert into O;
    end;
    """)
    half = len(TAPE) // 2

    def feed(rt, lo, hi, sink):
        h = rt.input_handler("S")
        for (row, ts) in TAPE[lo:hi]:
            h.send(row, timestamp=ts)
        rt.flush()

    m1 = SiddhiManager()
    r1 = m1.create_app_runtime(app)
    ref = []
    r1.add_callback("O", lambda evs: ref.extend(tuple(e.data) for e in evs))
    r1.start()
    feed(r1, 0, len(TAPE), None)
    m1.shutdown()

    m2 = SiddhiManager()
    r2 = m2.create_app_runtime(app)
    got = []
    r2.add_callback("O", lambda evs: got.extend(tuple(e.data) for e in evs))
    r2.start()
    feed(r2, 0, half, None)
    snap = r2.snapshot()
    m2.shutdown()
    m3 = SiddhiManager()
    r3 = m3.create_app_runtime(app)
    r3.add_callback("O", lambda evs: got.extend(tuple(e.data) for e in evs))
    r3.start()
    r3.restore(snap)
    feed(r3, half, len(TAPE), None)
    m3.shutdown()
    assert sorted(got) == sorted(ref)
