"""@app:enforceOrder (VERDICT r4 #7): restores cross-batch ordering when
@app:async runs multiple ingest workers (reference:
core:util/parser/SiddhiAppParser.java:94-98 — the reference wraps the
multi-worker junction so events process in arrival order)."""
import random
import time
import warnings

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

APP = ("define stream S (x int);\n"
       "from every e1=S[x == 0] -> e2=S[x == e1.x + 1] -> "
       "e3=S[x == e2.x + 1] select e3.x as v insert into Out;\n")


def _run(head, n=240, jitter=False):
    """Send n single-event batches 0,1,2,0,1,2,... — the 3-state sequence
    matches once per complete run ONLY when batches process in order.
    `jitter` widens the pop->process race window so multi-worker
    reordering actually manifests."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = SiddhiManager()
        rt = m.create_app_runtime(head + APP)
    rows = []
    rt.add_callback("Out", lambda evs: rows.extend(e.data for e in evs))
    rt.start()
    if jitter and rt._ingest_q is not None:
        orig_get = rt._ingest_q.get
        rng = random.Random(7)

        def slow_get(*a, **k):
            item = orig_get(*a, **k)
            time.sleep(rng.random() * 0.002)
            return item
        rt._ingest_q.get = slow_get
    h = rt.input_handler("S")
    for i in range(n):
        h.send_batch({"x": np.array([i % 3], np.int32)},
                     timestamps=np.array([1000 + i]))
    rt.flush()
    m.shutdown()
    return rows


def test_enforce_order_with_workers():
    rows = _run("@app:enforceOrder\n"
                "@app:async(workers='4', buffer.size='64')\n", jitter=True)
    assert len(rows) == 240 // 3, len(rows)


def test_without_enforce_order_emits_trade_warning():
    """The documented trade: workers>1 without the annotation does NOT
    guarantee cross-batch order (same as the reference junction) — the
    build warns and points at @app:enforceOrder.  (Actual reordering is
    scheduling-dependent and not deterministically assertable.)"""
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        m = SiddhiManager()
        m.create_app_runtime("@app:async(workers='4')\n"
                             "define stream S (x int);\n"
                             "from S select x insert into Out;\n")
        m.shutdown()
    assert any("enforceOrder" in str(x.message) for x in w)


def test_enforce_order_single_worker_noop():
    rows = _run("@app:enforceOrder\n@app:async\n")
    assert len(rows) == 240 // 3


def test_enforce_order_warning_suppressed():
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        m = SiddhiManager()
        m.create_app_runtime("@app:enforceOrder\n"
                             "@app:async(workers='4')\n"
                             "define stream S (x int);\n"
                             "from S select x insert into Out;\n")
        m.shutdown()
    assert not any("ordering is not preserved" in str(x.message) for x in w)
