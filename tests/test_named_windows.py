"""Named windows (`define window`) + on-demand (store) queries.

Reference test surface: modules/siddhi-core/src/test/java/org/wso2/siddhi/
core/window/ (WindowTestCase etc.) and query/storequery/StoreQueryTableTestCase.
"""
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.planner import PlanError


@pytest.fixture
def mgr():
    m = SiddhiManager()
    yield m
    m.shutdown()


def collect(rt, sid):
    out = []
    rt.add_callback(sid, lambda evs: out.extend(e.data for e in evs))
    return out


# -- named windows -----------------------------------------------------------

APP_W = """
    define stream S (sym string, price double);
    define window W (sym string, price double) length(2) output all events;
    from S select sym, price insert into W;
    from W select sym, price insert into O;
"""


def test_named_window_passthrough(mgr):
    rt = mgr.create_app_runtime(APP_W)
    out = collect(rt, "O")
    rt.input_handler("S").send([("A", 1.0), ("B", 2.0)])
    rt.flush()
    assert out == [("A", 1.0), ("B", 2.0)]


def test_named_window_aggregate_tracks_contents(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (sym string, price double);
        define window W (sym string, price double) length(2) output all events;
        from S select sym, price insert into W;
        from W select sum(price) as total insert into O;
    """)
    out = collect(rt, "O")
    h = rt.input_handler("S")
    h.send(("A", 1.0))
    h.send(("B", 2.0))
    h.send(("C", 10.0))     # displaces A -> sum over {B, C}
    rt.flush()
    # rows after each add/remove; final value must reflect window contents
    assert out[-1] == (12.0,)


def test_named_window_expired_output(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        define window W (x int) length(1) output all events;
        from S select x insert into W;
        from W select x insert expired events into O;
    """)
    out = collect(rt, "O")
    h = rt.input_handler("S")
    h.send((1,))
    h.send((2,))     # 1 expires
    rt.flush()
    assert out == [(1,)]


def test_two_queries_share_window(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        define window W (x int) lengthBatch(2);
        from S select x insert into W;
        from W select sum(x) as s insert into O1;
        from W[x > 1] select x insert into O2;
    """)
    o1, o2 = collect(rt, "O1"), collect(rt, "O2")
    rt.input_handler("S").send([(1,), (2,)])
    rt.flush()
    assert o1[-1] == (3,)
    assert o2 == [(2,)]


def test_named_window_reset_clears_aggregates(mgr):
    """lengthBatch with `output current events`: readers get no expired
    events, so the RESET signal must clear their aggregate banks."""
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        define window W (x int) lengthBatch(2) output current events;
        from S select x insert into W;
        from W select sum(x) as s insert into O;
    """)
    out = collect(rt, "O")
    rt.input_handler("S").send([(1,), (2,)])
    rt.flush()
    rt.input_handler("S").send([(3,), (4,)])
    rt.flush()
    # per-batch sums: (1),(3) then reset, (3),(7) — not cumulative (6),(10)
    assert out == [(1,), (3,), (3,), (7,)]


def test_join_against_named_window(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (sym string, price double);
        define stream Q (sym string);
        define window W (sym string, price double) length(10);
        from S select sym, price insert into W;
        from Q join W on W.sym == Q.sym
            select Q.sym as sym, W.price as price insert into O;
    """)
    out = collect(rt, "O")
    rt.input_handler("S").send([("A", 1.0), ("B", 2.0)])
    rt.flush()
    rt.input_handler("Q").send(("B",))
    rt.flush()
    assert out == [("B", 2.0)]


def test_no_input_handler_for_window(mgr):
    rt = mgr.create_app_runtime(APP_W)
    with pytest.raises(KeyError):
        rt.input_handler("W")


def test_window_on_named_window_rejected(mgr):
    with pytest.raises(PlanError):
        mgr.create_app_runtime("""
            define stream S (x int);
            define window W (x int) length(5);
            from S select x insert into W;
            from W#window.length(2) select x insert into O;
        """)


def test_named_window_snapshot(mgr):
    app = """
        define stream S (x int);
        define window W (x int) length(3);
        from S select x insert into W;
        from W select sum(x) as s insert into O;
    """
    rt = mgr.create_app_runtime(app)
    collect(rt, "O")
    rt.input_handler("S").send([(1,), (2,)])
    rt.flush()
    snap = rt.snapshot()

    m2 = SiddhiManager()
    rt2 = m2.create_app_runtime(app)
    rt2.restore(snap)
    assert [e.data for e in rt2.named_windows["W"].contents()] == [(1,), (2,)]
    m2.shutdown()


# -- store queries -----------------------------------------------------------

APP_STORE = """
    define stream S (sym string, price double, vol long);
    @PrimaryKey('sym')
    define table T (sym string, price double, vol long);
    from S select sym, price, vol insert into T;
"""


def _fill(rt):
    rt.input_handler("S").send([("A", 10.0, 100), ("B", 20.0, 200),
                                ("C", 30.0, 300)])
    rt.flush()


def test_store_query_find_all(mgr):
    rt = mgr.create_app_runtime(APP_STORE)
    _fill(rt)
    rows = sorted(r for _t, r in rt.query("from T select sym, price"))
    assert rows == [("A", 10.0), ("B", 20.0), ("C", 30.0)]


def test_store_query_on_condition(mgr):
    rt = mgr.create_app_runtime(APP_STORE)
    _fill(rt)
    rows = sorted(r for _t, r in
                  rt.query("from T on price > 15 select sym"))
    assert rows == [("B",), ("C",)]


def test_store_query_pk_seek(mgr):
    rt = mgr.create_app_runtime(APP_STORE)
    _fill(rt)
    rows = [r for _t, r in rt.query("from T on T.sym == 'B' select sym, vol")]
    assert rows == [("B", 200)]


def test_store_query_aggregate(mgr):
    rt = mgr.create_app_runtime(APP_STORE)
    _fill(rt)
    rows = [r for _t, r in rt.query("from T select sum(vol) as total")]
    assert rows == [(600,)]
    # re-execution starts fresh (no carried aggregate state)
    rows = [r for _t, r in rt.query("from T select sum(vol) as total")]
    assert rows == [(600,)]


def test_store_query_group_by(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (grp string, v int);
        define table T (grp string, v int);
        from S select grp, v insert into T;
    """)
    rt.input_handler("S").send([("a", 1), ("b", 2), ("a", 3)])
    rt.flush()
    rows = sorted(r for _t, r in rt.query(
        "from T select grp, sum(v) as s group by grp"))
    assert rows == [("a", 4), ("b", 2)]


def test_store_query_delete_action(mgr):
    rt = mgr.create_app_runtime(APP_STORE)
    _fill(rt)
    rt.query("from T on price > 15 select sym delete T on T.sym == sym")
    rows = sorted(r[0] for _t, r in rt.query("from T select sym"))
    assert rows == ["A"]


def test_store_query_update_action(mgr):
    rt = mgr.create_app_runtime(APP_STORE)
    _fill(rt)
    rt.query("from T on sym == 'A' select sym, price "
             "update T set T.price = 99.0 on T.sym == sym")
    rows = [r for _t, r in rt.query("from T on sym == 'A' select price")]
    assert rows == [(99.0,)]


def test_store_query_from_named_window(mgr):
    rt = mgr.create_app_runtime("""
        define stream S (x int);
        define window W (x int) length(5);
        from S select x insert into W;
    """)
    rt.input_handler("S").send([(1,), (2,), (3,)])
    rt.flush()
    rows = sorted(r for _t, r in rt.query("from W on x > 1 select x"))
    assert rows == [(2,), (3,)]


def test_store_query_unknown_source(mgr):
    rt = mgr.create_app_runtime(APP_STORE)
    with pytest.raises(PlanError):
        rt.query("from NoSuch select x")
