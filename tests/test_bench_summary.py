"""Bench driver-interface pins (ISSUE 13 satellites):

  * the final stdout line of every bench mode must round-trip through
    json.loads within the driver's tail-capture bound — _print_summary
    degrades by dropping detail keys and falls back to a minimal
    headline line rather than EVER printing an oversized/unparseable
    final line (the BENCH "parsed": null failure shape);
  * every latency/throughput frontier point gets a MEASURED p99 —
    p99_latency flushes unconditionally per timed batch, so a batch's
    deliveries land while its own clock is live and the histogram can
    never come back empty (the frontier "p99_ms": null shape, r05).
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _last_line(capsys) -> str:
    out = capsys.readouterr().out.strip()
    return out.splitlines()[-1]


def test_print_summary_small_passes_through(capsys):
    s = {"metric": "m", "value": 1, "unit": "u", "vs_baseline": 2.0}
    bench._print_summary(dict(s))
    assert json.loads(_last_line(capsys)) == s


def test_print_summary_oversize_degrades_to_parseable(capsys):
    s = {"metric": "m", "value": 1, "unit": "u", "vs_baseline": 2.0,
         "detail": "BENCH_DETAIL.json",
         "configs": {f"c{i}": {"eps": i, "note": "x" * 50}
                     for i in range(100)},
         "roofline": {"a": list(range(200))},
         "transport": {"b": "y" * 500},
         "placement": {"c": "z" * 300},
         "durability": {"d": "w" * 300},
         "stage_shares_config3": {"s": 1.0},
         "trace_coverage_config3": 0.97}
    bench._print_summary(dict(s), cap=512)
    line = _last_line(capsys)
    assert len(line) <= 512
    parsed = json.loads(line)
    assert parsed["metric"] == "m" and parsed["value"] == 1


def test_print_summary_oversize_beyond_drops_still_parses(capsys):
    # even the headline keys blow the cap: the minimal fallback line
    # must still print and parse (hard bound, never garbage)
    s = {"metric": "m" * 4000, "value": 1, "unit": "u",
         "vs_baseline": 2.0, "detail": "BENCH_DETAIL.json"}
    bench._print_summary(dict(s), cap=256)
    parsed = json.loads(_last_line(capsys))
    assert parsed["value"] == 1


def test_print_summary_nonserializable_falls_back(capsys):
    s = {"metric": "m", "value": 1, "unit": "u", "vs_baseline": 2.0,
         "detail": "BENCH_DETAIL.json", "configs": {"bad": object()}}
    bench._print_summary(dict(s))
    parsed = json.loads(_last_line(capsys))
    assert parsed["metric"] == "m" and parsed["value"] == 1


def test_p99_latency_always_measured():
    """The per-batch flush guarantees a measured histogram whenever the
    tape produces matches at all — no silent None."""
    tape = bench.make_tape(256 * 6, 256)
    p99 = bench.p99_latency(bench.DEV["patterns"] + bench.C3,
                            bench.STREAM, tape, 8, warm=2)
    assert isinstance(p99, float) and p99 >= 0.0


def test_frontier_every_point_has_measured_p99():
    pts = bench.frontier(bench.DEV["patterns"] + bench.C3,
                         host_app=None, batches=(256,))
    assert pts, "frontier returned no points"
    for pt in pts:
        assert "skipped" in pt or pt.get("p99_ms") is not None, pt
